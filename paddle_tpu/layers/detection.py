"""Detection layers (reference: ``python/paddle/fluid/layers/detection.py``).

Graph-DSL wrappers over the detection op family (ops/detection.py).  The
surface mirrors the reference's (prior_box :1381, density_prior_box :1495,
multi_box_head :1650, anchor_generator :1902, box_coder :564, yolo_box :750,
multiclass_nms :2381, box_clip :2200, iou_similarity :516, roi_align in
nn.py, sigmoid_focal_loss :294, polygon_box_transform :676) with TPU-static
shape semantics documented in ops/detection.py.
"""

import math

from ..layer_helper import LayerHelper
from ..framework import Variable

__all__ = [
    "prior_box",
    "density_prior_box",
    "anchor_generator",
    "box_coder",
    "box_clip",
    "iou_similarity",
    "yolo_box",
    "multiclass_nms",
    "roi_align",
    "sigmoid_focal_loss",
    "polygon_box_transform",
    "detection_output",
    "ssd_loss",
    "multi_box_head",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", **locals())
    dtype = "float32"
    if not isinstance(min_sizes, (list, tuple)):
        min_sizes = [min_sizes]
    attrs = {
        "min_sizes": [float(v) for v in min_sizes],
        "aspect_ratios": [float(v) for v in aspect_ratios],
        "variances": [float(v) for v in variance],
        "flip": flip,
        "clip": clip,
        "step_w": float(steps[0]),
        "step_h": float(steps[1]),
        "offset": float(offset),
        "min_max_aspect_ratios_order": min_max_aspect_ratios_order,
    }
    if max_sizes:
        if not isinstance(max_sizes, (list, tuple)):
            max_sizes = [max_sizes]
        assert len(max_sizes) == len(min_sizes), (
            "prior_box: max_sizes must pair 1:1 with min_sizes "
            "(got %d vs %d)" % (len(max_sizes), len(min_sizes)))
        attrs["max_sizes"] = [float(v) for v in max_sizes]
    box = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [box], "Variances": [var]},
        attrs=attrs,
    )
    box.stop_gradient = True
    var.stop_gradient = True
    return box, var


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    helper = LayerHelper("density_prior_box", **locals())
    dtype = "float32"
    box = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [box], "Variances": [var]},
        attrs={
            "densities": [int(v) for v in densities],
            "fixed_sizes": [float(v) for v in fixed_sizes],
            "fixed_ratios": [float(v) for v in fixed_ratios],
            "variances": [float(v) for v in variance],
            "clip": clip,
            "step_w": float(steps[0]),
            "step_h": float(steps[1]),
            "offset": float(offset),
        },
    )
    if flatten_to_2d:
        from .nn import reshape

        box = reshape(box, shape=[-1, 4])
        var = reshape(var, shape=[-1, 4])
    box.stop_gradient = True
    var.stop_gradient = True
    return box, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", **locals())
    dtype = "float32"
    anchor_sizes = anchor_sizes or [64.0, 128.0, 256.0, 512.0]
    aspect_ratios = aspect_ratios or [0.5, 1.0, 2.0]
    stride = stride or [16.0, 16.0]
    anchors = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="anchor_generator",
        inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={
            "anchor_sizes": [float(v) for v in anchor_sizes],
            "aspect_ratios": [float(v) for v in aspect_ratios],
            "variances": [float(v) for v in variance],
            "stride": [float(v) for v in stride],
            "offset": float(offset),
        },
    )
    anchors.stop_gradient = True
    var.stop_gradient = True
    return anchors, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    helper = LayerHelper("box_coder", **locals())
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if isinstance(prior_box_var, Variable):
        inputs["PriorBoxVar"] = [prior_box_var]
    elif isinstance(prior_box_var, (list, tuple)):
        attrs["variance"] = [float(v) for v in prior_box_var]
    helper.append_op(
        type="box_coder", inputs=inputs, outputs={"OutputBox": [out]},
        attrs=attrs)
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="box_clip",
        inputs={"Input": [input], "ImInfo": [im_info]},
        outputs={"Output": [out]},
    )
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="iou_similarity",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"box_normalized": box_normalized},
    )
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             name=None):
    helper = LayerHelper("yolo_box", **locals())
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="yolo_box",
        inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes], "Scores": [scores]},
        attrs={
            "anchors": [int(v) for v in anchors],
            "class_num": int(class_num),
            "conf_thresh": float(conf_thresh),
            "downsample_ratio": int(downsample_ratio),
        },
    )
    boxes.stop_gradient = True
    scores.stop_gradient = True
    return boxes, scores


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None, return_rois_num=False):
    """Fixed-shape NMS: Out is [N, keep_top_k, 6] padded with -1 rows
    (the reference returns a ragged LoDTensor — see ops/detection.py)."""
    helper = LayerHelper("multiclass_nms", **locals())
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "NmsRoisNum": [num]},
        attrs={
            "background_label": int(background_label),
            "score_threshold": float(score_threshold),
            "nms_top_k": int(nms_top_k),
            "keep_top_k": int(keep_top_k),
            "nms_threshold": float(nms_threshold),
            "nms_eta": float(nms_eta),
            "normalized": normalized,
        },
    )
    out.stop_gradient = True
    num.stop_gradient = True
    if return_rois_num:
        return out, num
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None, name=None):
    helper = LayerHelper("roi_align", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num]
    helper.append_op(
        type="roi_align",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "pooled_height": int(pooled_height),
            "pooled_width": int(pooled_width),
            "spatial_scale": float(spatial_scale),
            "sampling_ratio": int(sampling_ratio),
        },
    )
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25, name=None):
    helper = LayerHelper("sigmoid_focal_loss", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_focal_loss",
        inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
        outputs={"Out": [out]},
        attrs={"gamma": float(gamma), "alpha": float(alpha)},
    )
    return out


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="polygon_box_transform",
        inputs={"Input": [input]},
        outputs={"Output": [out]},
    )
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_rois_num=False):
    """SSD inference head (reference detection.py:440): decode loc against
    priors, then multiclass NMS.  loc [N, P, 4]; scores [N, P, C];
    prior_box [P, 4] (flattened)."""
    from .nn import transpose

    decoded = box_coder(
        prior_box=prior_box,
        prior_box_var=prior_box_var,
        target_box=loc,
        code_type="decode_center_size",
    )
    cls_scores = transpose(scores, perm=[0, 2, 1])  # [N, C, P]
    return multiclass_nms(
        bboxes=decoded,
        scores=cls_scores,
        score_threshold=score_threshold,
        nms_top_k=nms_top_k,
        keep_top_k=keep_top_k,
        nms_threshold=nms_threshold,
        nms_eta=nms_eta,
        background_label=background_label,
        return_rois_num=return_rois_num,
    )


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, mining_type="max_negative",
             normalize=True, sample_size=None):
    """Simplified SSD training loss with static shapes.

    The reference composes bipartite_match + target_assign +
    mine_hard_examples (detection.py:1074).  TPU-static version (ssd_loss
    op): per-prior argmax matching against padded gt boxes (gt padded
    with zero-area boxes, label slot [N, G] with -1 padding),
    hard-negative mining by per-image rank under a
    ceil(neg_pos_ratio·npos) budget.  Returns the [N, P, 1] per-prior
    weighted loss (reduce it for the scalar objective)."""
    if mining_type != "max_negative":
        raise ValueError("ssd_loss supports mining_type='max_negative'")
    helper = LayerHelper("ssd_loss", **locals())
    out = helper.create_variable_for_type_inference(location.dtype)
    inputs = {"Loc": [location], "Conf": [confidence], "GTBox": [gt_box],
              "GTLabel": [gt_label], "PriorBox": [prior_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="ssd_loss", inputs=inputs, outputs={"Loss": [out]},
        attrs={
            "background_label": int(background_label),
            "overlap_threshold": float(overlap_threshold),
            "neg_pos_ratio": float(neg_pos_ratio),
            "neg_overlap": float(neg_overlap),
            "loc_loss_weight": float(loc_loss_weight),
            "conf_loss_weight": float(conf_loss_weight),
            "normalize": bool(normalize),
        },
    )
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multi-feature-map head (reference detection.py:1650): per input
    feature map, a conv predicting loc+conf and a prior_box; results are
    flattened and concatenated."""
    from .nn import conv2d, transpose, reshape, concat

    n_layer = len(inputs)
    if n_layer <= 2:
        # reference requires explicit sizes for <=2 maps (detection.py:1650)
        assert min_sizes is not None and max_sizes is not None, (
            "multi_box_head with <=2 feature maps needs explicit "
            "min_sizes/max_sizes")
    elif min_sizes is None:
        # reference formula: evenly spaced ratios of base_size
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) / (n_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, input in enumerate(inputs):
        min_size = min_sizes[i]
        max_size = max_sizes[i] if max_sizes else None
        if not isinstance(min_size, list):
            min_size = [min_size]
        if max_size is not None and not isinstance(max_size, list):
            max_size = [max_size]
        aspect_ratio = aspect_ratios[i]
        if not isinstance(aspect_ratio, list):
            aspect_ratio = [aspect_ratio]
        step = [step_w[i] if step_w else 0.0, step_h[i] if step_h else 0.0] \
            if (step_w or step_h) else (steps[i] if steps else [0.0, 0.0])
        if not isinstance(step, (list, tuple)):
            step = [step, step]

        box, var = prior_box(
            input, image, min_size, max_size, aspect_ratio, variance, flip,
            clip, step, offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        num_priors_per_cell = box.shape[2]

        num_loc_output = num_priors_per_cell * 4
        mbox_loc = conv2d(input, num_filters=num_loc_output,
                          filter_size=kernel_size, padding=pad, stride=stride)
        mbox_loc = transpose(mbox_loc, perm=[0, 2, 3, 1])
        locs.append(reshape(mbox_loc, shape=[0, -1, 4]))

        num_conf_output = num_priors_per_cell * num_classes
        conf = conv2d(input, num_filters=num_conf_output,
                      filter_size=kernel_size, padding=pad, stride=stride)
        conf = transpose(conf, perm=[0, 2, 3, 1])
        confs.append(reshape(conf, shape=[0, -1, num_classes]))

        boxes_l.append(reshape(box, shape=[-1, 4]))
        vars_l.append(reshape(var, shape=[-1, 4]))

    mbox_locs = concat(locs, axis=1)
    mbox_confs = concat(confs, axis=1)
    box = concat(boxes_l, axis=0)
    var = concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, box, var
