"""Operator-overload sugar for Variables (reference:
``python/paddle/fluid/layers/math_op_patch.py``)."""

from ..framework import Variable
from ..layer_helper import LayerHelper


_SCALAR_SHORTCUTS = {
    "elementwise_add": lambda s: {"scale": 1.0, "bias": float(s)},
    "elementwise_sub": lambda s: {"scale": 1.0, "bias": -float(s)},
    "elementwise_mul": lambda s: {"scale": float(s), "bias": 0.0},
}


def binary_op(x, other, op_type, reverse=False):
    helper = LayerHelper(op_type)
    if not isinstance(other, Variable):
        s = float(other)
        # scalar fast paths lower to one fused `scale` op
        if not reverse and op_type in _SCALAR_SHORTCUTS:
            attrs = _SCALAR_SHORTCUTS[op_type](s)
            out = helper.create_variable_for_type_inference(x.dtype)
            helper.append_op(
                type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                attrs=attrs,
            )
            return out
        if not reverse and op_type == "elementwise_div":
            out = helper.create_variable_for_type_inference(x.dtype)
            helper.append_op(
                type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                attrs={"scale": 1.0 / s, "bias": 0.0},
            )
            return out
        from .tensor import fill_constant

        other = fill_constant([1], x.dtype, s)
    a, b = (other, x) if reverse else (x, other)
    out = helper.create_variable_for_type_inference(
        a.dtype if isinstance(a, Variable) else b.dtype
    )
    helper.append_op(
        type=op_type,
        inputs={"X": [a], "Y": [b]},
        outputs={"Out": [out]},
        attrs={"axis": -1},
    )
    return out
