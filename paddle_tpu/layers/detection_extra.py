"""Detection-training layer wrappers (reference
``python/paddle/fluid/layers/detection.py``) over the registered ops in
ops/detection.py."""

from ..framework import Variable
from ..layer_helper import LayerHelper
from .nn_extra import _simple

__all__ = [
    "yolov3_loss", "rpn_target_assign", "retinanet_target_assign",
    "bipartite_match", "target_assign", "generate_proposals",
    "distribute_fpn_proposals", "collect_fpn_proposals",
    "box_decoder_and_assign", "roi_perspective_transform",
    "generate_proposal_labels", "generate_mask_labels",
    "retinanet_detection_output",
]


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """reference detection.py yolov3_loss → yolov3_loss op."""
    loss, _, _ = _simple(
        "yolov3_loss",
        {"X": x, "GTBox": gt_box, "GTLabel": gt_label,
         "GTScore": gt_score},
        {"anchors": [int(a) for a in anchors],
         "anchor_mask": [int(a) for a in anchor_mask],
         "class_num": int(class_num),
         "ignore_thresh": float(ignore_thresh),
         "downsample_ratio": int(downsample_ratio),
         "use_label_smooth": bool(use_label_smooth)},
        outs=("Loss", "ObjectnessMask", "GTMatchMask"))
    return loss


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """reference detection.py rpn_target_assign → rpn_target_assign op
    (deterministic capped selection instead of random subsampling)."""
    loc_idx, score_idx, tgt_lbl, tgt_bbox, inside_w = _simple(
        "rpn_target_assign",
        {"Anchor": anchor_box, "GtBoxes": gt_boxes,
         "IsCrowd": is_crowd, "ImInfo": im_info},
        {"rpn_batch_size_per_im": int(rpn_batch_size_per_im),
         "rpn_positive_overlap": float(rpn_positive_overlap),
         "rpn_negative_overlap": float(rpn_negative_overlap)},
        outs=("LocationIndex", "ScoreIndex", "TargetLabel", "TargetBBox",
              "BBoxInsideWeight"),
        stop_gradient=True)
    return loc_idx, score_idx, tgt_lbl, tgt_bbox, inside_w


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4):
    """reference detection.py retinanet_target_assign."""
    outs = _simple(
        "retinanet_target_assign",
        {"Anchor": anchor_box, "GtBoxes": gt_boxes,
         "GtLabels": gt_labels, "IsCrowd": is_crowd, "ImInfo": im_info},
        {"positive_overlap": float(positive_overlap),
         "negative_overlap": float(negative_overlap)},
        outs=("LocationIndex", "ScoreIndex", "TargetLabel", "TargetBBox",
              "BBoxInsideWeight", "ForegroundNumber"),
        stop_gradient=True)
    return outs


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """reference detection.py bipartite_match."""
    idx, dist = _simple(
        "bipartite_match", {"DistMat": dist_matrix},
        {"match_type": match_type or "bipartite",
         "dist_threshold": float(dist_threshold or 0.5)},
        outs=("ColToRowMatchIndices", "ColToRowMatchDist"),
        stop_gradient=True)
    return idx, dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """reference detection.py target_assign."""
    out, w = _simple(
        "target_assign",
        {"X": input, "MatchIndices": matched_indices,
         "NegIndices": negative_indices},
        {"mismatch_value": mismatch_value or 0},
        outs=("Out", "OutWeight"), stop_gradient=True)
    return out, w


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """reference detection.py generate_proposals."""
    rois, probs = _simple(
        "generate_proposals",
        {"Scores": scores, "BboxDeltas": bbox_deltas, "ImInfo": im_info,
         "Anchors": anchors, "Variances": variances},
        {"pre_nms_topN": int(pre_nms_top_n),
         "post_nms_topN": int(post_nms_top_n),
         "nms_thresh": float(nms_thresh), "min_size": float(min_size)},
        outs=("RpnRois", "RpnRoiProbs"), stop_gradient=True)
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    """reference detection.py distribute_fpn_proposals."""
    helper = LayerHelper("distribute_fpn_proposals", **locals())
    n_levels = max_level - min_level + 1
    outs = [helper.create_variable_for_type_inference(fpn_rois.dtype, True)
            for _ in range(n_levels)]
    restore = helper.create_variable_for_type_inference("int32", True)
    helper.append_op(
        type="distribute_fpn_proposals",
        inputs={"FpnRois": [fpn_rois]},
        outputs={"MultiFpnRois": [o.name for o in outs],
                 "RestoreIndex": [restore]},
        attrs={"min_level": int(min_level), "max_level": int(max_level),
               "refer_level": int(refer_level),
               "refer_scale": float(refer_scale)},
    )
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    """reference detection.py collect_fpn_proposals."""
    return _simple(
        "collect_fpn_proposals",
        {"MultiLevelRois": list(multi_rois),
         "MultiLevelScores": list(multi_scores)},
        {"post_nms_topN": int(post_nms_top_n)},
        outs=("FpnRois",), stop_gradient=True)


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=None, name=None):
    """reference detection.py box_decoder_and_assign."""
    dec, assigned = _simple(
        "box_decoder_and_assign",
        {"PriorBox": prior_box, "PriorBoxVar": prior_box_var,
         "TargetBox": target_box, "BoxScore": box_score}, {},
        outs=("DecodeBox", "OutputAssignBox"), stop_gradient=True)
    return dec, assigned


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              name=None):
    """reference detection.py roi_perspective_transform."""
    outs = _simple(
        "roi_perspective_transform", {"X": input, "ROIs": rois},
        {"transformed_height": int(transformed_height),
         "transformed_width": int(transformed_width),
         "spatial_scale": float(spatial_scale)},
        outs=("Out", "Mask", "TransformMatrix", "Out2InIdx",
              "Out2InWeights"),
        stop_gradient=True)
    return outs[0]


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """reference detection.py generate_proposal_labels →
    generate_proposal_labels op (deterministic capped fg/bg sampling)."""
    outs = _simple(
        "generate_proposal_labels",
        {"RpnRois": rpn_rois, "GtClasses": gt_classes,
         "IsCrowd": is_crowd, "GtBoxes": gt_boxes, "ImInfo": im_info},
        {"batch_size_per_im": int(batch_size_per_im),
         "fg_fraction": float(fg_fraction), "fg_thresh": float(fg_thresh),
         "bg_thresh_hi": float(bg_thresh_hi),
         "bg_thresh_lo": float(bg_thresh_lo),
         "bbox_reg_weights": [float(w) for w in bbox_reg_weights],
         "class_nums": int(class_nums or 81)},
        outs=("Rois", "LabelsInt32", "BboxTargets", "BboxInsideWeights",
              "BboxOutsideWeights"),
        stop_gradient=True)
    return outs


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    """reference detection.py generate_mask_labels →
    generate_mask_labels op.  Deviation: gt_segms are PRE-RASTERIZED
    [G, H, W] masks (the reference takes COCO polygons; polygon
    rasterization is host preprocessing, not device work)."""
    outs = _simple(
        "generate_mask_labels",
        {"ImInfo": im_info, "GtClasses": gt_classes, "IsCrowd": is_crowd,
         "GtSegms": gt_segms, "Rois": rois, "LabelsInt32": labels_int32},
        {"num_classes": int(num_classes), "resolution": int(resolution)},
        outs=("MaskRois", "RoiHasMaskInt32", "MaskInt32"),
        stop_gradient=True)
    return outs


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """reference detection.py retinanet_detection_output →
    retinanet_detection_output op."""
    return _simple(
        "retinanet_detection_output",
        {"BBoxes": list(bboxes) if isinstance(bboxes, (list, tuple))
         else [bboxes],
         "Scores": list(scores) if isinstance(scores, (list, tuple))
         else [scores],
         "Anchors": list(anchors) if isinstance(anchors, (list, tuple))
         else [anchors],
         "ImInfo": im_info},
        {"score_threshold": float(score_threshold),
         "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
         "nms_threshold": float(nms_threshold), "nms_eta": float(nms_eta)},
        stop_gradient=True)
