"""In-graph metric layers (reference:
``python/paddle/fluid/layers/metric_op.py`` → ``operators/metrics/``)."""

from ..layer_helper import LayerHelper

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy", **locals())
    topk_out = helper.create_variable_for_type_inference(input.dtype)
    topk_indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": k},
    )
    acc_out = helper.create_variable_for_type_inference("float32", True)
    if correct is None:
        correct = helper.create_variable_for_type_inference("int64", True)
    if total is None:
        total = helper.create_variable_for_type_inference("int64", True)
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices],
                "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct],
                 "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming AUC (reference metric_op.py:78): returns
    (global_auc, batch_auc, [batch_stat_pos, batch_stat_neg, stat_pos,
    stat_neg]).  Stat vars are persistable accumulators threaded through the
    auc op functionally (StatPos in → StatPosOut back to the same var)."""
    from ..initializer import ConstantInitializer

    helper = LayerHelper("auc", **locals())
    auc_out = helper.create_variable_for_type_inference("float32", True)
    batch_auc_out = helper.create_variable_for_type_inference("float32", True)

    # slide_steps == 0 → batch stats accumulate globally (reference
    # semantics: batch AUC then equals the global AUC); int64 stats match
    # the reference (auc_op.cc) — exact width on device follows
    # jax_enable_x64
    slide = max(int(slide_steps), 1)
    batch_stat_pos = helper.create_global_variable(
        persistable=True, dtype="int64", shape=[slide, num_thresholds + 1])
    batch_stat_neg = helper.create_global_variable(
        persistable=True, dtype="int64", shape=[slide, num_thresholds + 1])
    stat_pos = helper.create_global_variable(
        persistable=True, dtype="int64", shape=[1, num_thresholds + 1])
    stat_neg = helper.create_global_variable(
        persistable=True, dtype="int64", shape=[1, num_thresholds + 1])
    for var in [batch_stat_pos, batch_stat_neg, stat_pos, stat_neg]:
        helper.set_variable_initializer(var, ConstantInitializer(0.0))

    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label],
                "StatPos": [batch_stat_pos], "StatNeg": [batch_stat_neg]},
        attrs={"curve": curve, "num_thresholds": num_thresholds,
               "slide_steps": int(slide_steps)},
        outputs={"AUC": [batch_auc_out], "StatPosOut": [batch_stat_pos],
                 "StatNegOut": [batch_stat_neg]},
    )
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label],
                "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        attrs={"curve": curve, "num_thresholds": num_thresholds,
               "slide_steps": 0},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
    )
    return (auc_out, batch_auc_out,
            [batch_stat_pos, batch_stat_neg, stat_pos, stat_neg])
