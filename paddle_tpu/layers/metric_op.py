"""In-graph metric layers (reference:
``python/paddle/fluid/layers/metric_op.py`` → ``operators/metrics/``)."""

from ..layer_helper import LayerHelper

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy", **locals())
    topk_out = helper.create_variable_for_type_inference(input.dtype)
    topk_indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": k},
    )
    acc_out = helper.create_variable_for_type_inference("float32", True)
    if correct is None:
        correct = helper.create_variable_for_type_inference("int64", True)
    if total is None:
        total = helper.create_variable_for_type_inference("int64", True)
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices],
                "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct],
                 "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    raise NotImplementedError("auc op lands with the CTR/metrics batch")
