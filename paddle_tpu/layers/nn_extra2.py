"""Third wave of the layers.nn surface: RNN cells, CRF/CTC, sampled
softmax family, 3-D conv/pool, sequence extras, CTR helpers (reference
``python/paddle/fluid/layers/nn.py``)."""

from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from ..initializer import ConstantInitializer
from .nn_extra import _simple

__all__ = [
    "lstm_unit", "gru_unit", "dynamic_lstmp", "lstm",
    "linear_chain_crf", "crf_decoding", "chunk_eval",
    "edit_distance", "ctc_greedy_decoder", "warpctc",
    "nce", "hsigmoid", "sampled_softmax_with_cross_entropy",
    "conv3d", "conv3d_transpose", "pool3d", "adaptive_pool2d",
    "adaptive_pool3d",
    "sequence_conv", "sequence_expand_as", "sequence_reshape",
    "sequence_scatter",
    "continuous_value_model", "get_tensor_from_selected_rows",
    "merge_selected_rows", "py_func", "tree_conv", "similarity_focus",
    "deformable_conv", "deformable_roi_pooling", "host_embedding",
]


# ---- RNN cells ----------------------------------------------------------

def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference nn.py lstm_unit: fc([x, h]) -> 4D gates -> lstm_unit op
    (lstm_unit_op.h; gate order i,f,o,g)."""
    from . import nn as _nn

    d = cell_t_prev.shape[-1]
    concat = _nn.concat([x_t, hidden_t_prev], axis=1)
    gates = _nn.fc(concat, size=4 * d, param_attr=param_attr,
                   bias_attr=bias_attr)
    helper = LayerHelper("lstm_unit", **locals())
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [gates], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": float(forget_bias)},
    )
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """reference nn.py gru_unit → gru_unit_op.h; size = 3*D."""
    helper = LayerHelper("gru_unit", **locals())
    d = size // 3
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[d, 3 * d], dtype=input.dtype,
        is_bias=False)
    inputs = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[1, 3 * d], dtype=input.dtype,
            is_bias=True)
        inputs["Bias"] = [b]
    gate = helper.create_variable_for_type_inference(input.dtype, True)
    rhp = helper.create_variable_for_type_inference(input.dtype, True)
    hid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gru_unit", inputs=inputs,
        outputs={"Gate": [gate], "ResetHiddenPrev": [rhp], "Hidden": [hid]},
        attrs={"activation": activation, "gate_activation": gate_activation,
               "origin_mode": origin_mode},
    )
    return hid, rhp, gate


def host_embedding(input, size, name, lr=0.1, optimizer="sgd",
                   dtype="float32", initializer=None, seed=0):
    """Bigger-than-HBM embedding lookup against a HOST-resident table
    (the CTR capability of the reference's distributed lookup table:
    ``operators/distributed/parameter_prefetch.cc`` remote prefetch +
    ``communicator.h:160`` async push, redesigned pserver-free).

    The table (``size=[rows, dim]``) lives in host RAM
    (``paddle_tpu.host_table``), never on the accelerator.  The executor
    prefetches the batch's rows into a dense slab fed to the jitted
    step, fetches the slab gradient, and applies the sparse update on a
    background thread overlapped with the next step.  ``input`` must be
    a directly-fed data Variable of int ids (the prefetch reads its
    value before the device step); use the plain ``Executor`` path.
    The sparse optimizer (``sgd`` or ``adagrad``, own ``lr``) is a
    property of the table, like the reference pserver's optimizer
    blocks."""
    from .. import host_table

    rows, dim = int(size[0]), int(size[1])
    host_table.get_or_create(name, rows, dim, dtype=dtype, lr=lr,
                             optimizer=optimizer, initializer=initializer,
                             seed=seed)
    block = default_main_program().current_block()
    if block.idx != 0:
        raise ValueError("host_embedding must sit in the top-level block "
                         "(the prefetch runs around the whole jitted step)")
    slab_name = "%s@SLAB@%s" % (name, input.name)
    slab = block.create_var(
        name=slab_name,
        shape=list(input.shape) + [dim],
        dtype=dtype,
        stop_gradient=False,
        is_data=True,
    )
    prog = block.program
    if not hasattr(prog, "_host_tables"):
        prog._host_tables = []
    prog._host_tables.append(
        {"table": name, "ids": input.name, "slab": slab_name})
    return slab


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None,
                  seq_len=None):
    """reference nn.py:727 dynamic_lstmp → lstmp_op.h.  Padded [B,T,4D]
    pre-projected input + seq_len (LoD replacement); weight [P,4D],
    projection [D,P].  ``use_peepholes``/``proj_activation`` defaults
    match the reference (True / tanh); peepholes widen Bias to 7D."""
    helper = LayerHelper("dynamic_lstmp", **locals())
    d = size // 4
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[proj_size, 4 * d], dtype=dtype,
        is_bias=False)
    pw = helper.create_parameter(
        attr=ParamAttr(name=(helper.param_attr.name + ".proj")
                       if helper.param_attr.name else None),
        shape=[d, proj_size], dtype=dtype, is_bias=False)
    inputs = {"Input": [input], "Weight": [w], "ProjWeight": [pw]}
    if bias_attr is not False:
        bias_width = 7 * d if use_peepholes else 4 * d
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[1, bias_width], dtype=dtype,
            is_bias=True)
        inputs["Bias"] = [b]
    elif use_peepholes:
        raise ValueError("dynamic_lstmp(use_peepholes=True) requires a "
                         "bias (bias_attr must not be False)")
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op(
        type="dynamic_lstmp", inputs=inputs,
        outputs={"Projection": [proj], "Cell": [cell]},
        attrs={"use_peepholes": bool(use_peepholes),
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation,
               "is_reverse": is_reverse},
    )
    return proj, cell


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """reference nn.py lstm (cudnn_lstm_op): multi-layer LSTM over padded
    [B,T,D]; composed from the framework's lstm op per layer/direction
    (XLA fuses the scan; there is no cuDNN algorithm surface)."""
    from . import nn as _nn

    from . import tensor as _tensor

    helper = LayerHelper("cudnn_lstm", **locals())
    x = input
    ndirs = 2 if is_bidirec else 1
    last_hs, last_cs = [], []
    for layer in range(num_layers):
        outs = []
        for direction in range(ndirs):
            gates = _nn.fc(
                x, size=4 * hidden_size, num_flatten_dims=2,
                param_attr=ParamAttr(
                    name="%s_l%d_d%d.w" % (helper.name, layer, direction)),
                bias_attr=ParamAttr(
                    name="%s_l%d_d%d.b" % (helper.name, layer, direction)))
            h = helper.create_variable_for_type_inference(input.dtype)
            c = helper.create_variable_for_type_inference(input.dtype, True)
            wh = helper.create_parameter(
                attr=ParamAttr(
                    name="%s_l%d_d%d.wh" % (helper.name, layer, direction)),
                shape=[hidden_size, 4 * hidden_size], dtype=input.dtype,
                is_bias=False)
            inputs = {"Input": [gates], "Weight": [wh]}
            slot = layer * ndirs + direction
            if init_h is not None:
                h0 = _nn.squeeze(_nn.slice(
                    init_h, axes=[0], starts=[slot], ends=[slot + 1]),
                    axes=[0])
                inputs["H0"] = [h0]
            if init_c is not None:
                c0 = _nn.squeeze(_nn.slice(
                    init_c, axes=[0], starts=[slot], ends=[slot + 1]),
                    axes=[0])
                inputs["C0"] = [c0]
            helper.append_op(
                type="lstm",
                inputs=inputs,
                outputs={"Hidden": [h], "Cell": [c]},
                attrs={"is_reverse": direction == 1},
            )
            outs.append(h)
            # final state: last valid step of the scan (step 0 of a
            # reversed direction, since outputs are re-flipped)
            t_last = 0 if direction == 1 else (input.shape[1] - 1)
            for seq, acc in ((h, last_hs), (c, last_cs)):
                v = _nn.squeeze(_nn.slice(
                    seq, axes=[1], starts=[t_last], ends=[t_last + 1]),
                    axes=[1])
                acc.append(v)
        x = outs[0] if len(outs) == 1 else _nn.concat(outs, axis=2)
        # cuDNN semantics: dropout BETWEEN layers only, never on the
        # final layer's output
        if dropout_prob and not is_test and layer < num_layers - 1:
            x = _nn.dropout(x, dropout_prob,
                            dropout_implementation="upscale_in_train")
    last_h = _nn.stack(last_hs, axis=0)  # [L*dirs, B, D]
    last_c = _nn.stack(last_cs, axis=0)
    return x, last_h, last_c


# ---- CRF / CTC ----------------------------------------------------------

def linear_chain_crf(input, label, param_attr=None, length=None):
    """reference nn.py linear_chain_crf → linear_chain_crf_op.h; padded
    [B,T,D] emissions + length tensor (LoD replacement)."""
    helper = LayerHelper("linear_chain_crf", **locals())
    num_tags = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[num_tags + 2, num_tags],
        dtype=input.dtype, is_bias=False)
    inputs = {"Emission": [input], "Transition": [transition],
              "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    alpha = helper.create_variable_for_type_inference(input.dtype, True)
    ee = helper.create_variable_for_type_inference(input.dtype, True)
    te = helper.create_variable_for_type_inference(input.dtype, True)
    ll = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="linear_chain_crf", inputs=inputs,
        outputs={"Alpha": [alpha], "EmissionExps": [ee],
                 "TransitionExps": [te], "LogLikelihood": [ll]},
    )
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """reference nn.py crf_decoding → crf_decoding_op.h (viterbi)."""
    helper = LayerHelper("crf_decoding", **locals())
    transition = helper.main_program.global_block().var(param_attr.name)
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    if length is not None:
        inputs["Length"] = [length]
    path = helper.create_variable_for_type_inference("int64", True)
    helper.append_op(
        type="crf_decoding", inputs=inputs,
        outputs={"ViterbiPath": [path]},
    )
    return path


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """reference nn.py chunk_eval → chunk_eval_op.h"""
    helper = LayerHelper("chunk_eval", **locals())
    outs = {}
    names = ["Precision", "Recall", "F1-Score", "NumInferChunks",
             "NumLabelChunks", "NumCorrectChunks"]
    ret = []
    for nm in names:
        dt = "float32" if nm in ("Precision", "Recall", "F1-Score") \
            else "int64"
        v = helper.create_variable_for_type_inference(dt, True)
        outs[nm] = [v]
        ret.append(v)
    inputs = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        inputs["SeqLength"] = [seq_length]
    helper.append_op(
        type="chunk_eval", inputs=inputs, outputs=outs,
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": int(num_chunk_types),
               "excluded_chunk_types": list(excluded_chunk_types or [])},
    )
    return tuple(ret)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """reference nn.py edit_distance → edit_distance_op.h (padded)."""
    out, seq_num = _simple(
        "edit_distance",
        {"Hyps": input, "Refs": label, "HypsLength": input_length,
         "RefsLength": label_length},
        {"normalized": bool(normalized),
         "ignored_tokens": [int(t) for t in (ignored_tokens or [])]},
        out_dtype="float32", outs=("Out", "SequenceNum"),
        stop_gradient=True)
    return out, seq_num


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """reference nn.py ctc_greedy_decoder: argmax + ctc_align collapse
    (ctc_align_op.h); padded [B,T,C] probs + lengths."""
    from . import nn as _nn

    ids = _nn.argmax(input, axis=-1)
    out, out_len = _simple(
        "ctc_align", {"Input": ids, "InputLength": input_length},
        {"blank": int(blank), "padding_value": int(padding_value)},
        out_dtype="int64", outs=("Output", "OutputLength"),
        stop_gradient=True)
    return out, out_len


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """reference nn.py warpctc → warpctc_op (padded logits [B,T,C] +
    labels [B,L] + length tensors; softmax applied inside like
    warp-ctc)."""
    grad, loss = _simple(
        "warpctc",
        {"Logits": input, "Label": label, "LogitsLength": input_length,
         "LabelLength": label_length},
        {"blank": int(blank), "norm_by_times": bool(norm_by_times)},
        outs=("WarpCTCGrad", "Loss"))
    return loss


# ---- sampled softmax family --------------------------------------------

def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """reference nn.py nce → nce_op.h"""
    helper = LayerHelper("nce", **locals())
    dim = input.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_total_classes, dim],
        dtype=input.dtype, is_bias=False)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[num_total_classes, 1],
            dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    cost = helper.create_variable_for_type_inference(input.dtype)
    sl = helper.create_variable_for_type_inference(input.dtype, True)
    slab = helper.create_variable_for_type_inference("int64", True)
    sampler_id = {"uniform": 0, "log_uniform": 1}.get(sampler, 0)
    helper.append_op(
        type="nce", inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [sl],
                 "SampleLabels": [slab]},
        attrs={"num_total_classes": int(num_total_classes),
               "num_neg_samples": int(num_neg_samples),
               "sampler": sampler_id, "seed": seed},
    )
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None,
             is_custom=False, is_sparse=False):
    """reference nn.py hsigmoid → hierarchical_sigmoid_op.h (complete
    binary SimpleCode tree; custom trees unsupported on TPU — static
    shapes need the default tree)."""
    if is_custom or path_table is not None:
        raise NotImplementedError(
            "hsigmoid custom trees: the SimpleCode complete binary tree "
            "is the TPU-static path")
    helper = LayerHelper("hierarchical_sigmoid", **locals())
    dim = input.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_classes - 1, dim],
        dtype=input.dtype, is_bias=False)
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[num_classes - 1, 1],
            dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        type="hierarchical_sigmoid", inputs=inputs,
        outputs={"Out": [out], "PreOut": [pre]},
        attrs={"num_classes": int(num_classes)},
    )
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """reference nn.py sampled_softmax_with_cross_entropy →
    sample_logits_op + softmax pipeline (single fused op here)."""
    _, loss = _simple(
        "sampled_softmax_with_cross_entropy",
        {"Logits": logits, "Label": label},
        {"num_samples": int(num_samples), "seed": seed},
        outs=("Softmax", "Loss"))
    return loss


# ---- 3-D conv / pool ----------------------------------------------------

def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None):
    """reference nn.py conv3d → conv_op.cc 3-D registration."""
    helper = LayerHelper("conv3d", **locals())
    dtype = helper.input_dtype()

    def triple(v):
        return [int(v)] * 3 if isinstance(v, int) else [int(a) for a in v]

    stride, padding, dilation = (triple(stride), triple(padding),
                                 triple(dilation))
    filter_size = triple(filter_size)
    c_in = input.shape[1]
    g = groups or 1
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[num_filters, c_in // g] + filter_size, dtype=dtype,
        is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": g},
    )
    pre_act = helper.append_bias_op(out, dim_start=1)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """reference nn.py conv3d_transpose → conv_transpose_op.cc 3-D."""
    helper = LayerHelper("conv3d_transpose", **locals())
    dtype = helper.input_dtype()

    if filter_size is None:
        raise NotImplementedError(
            "conv3d_transpose: pass filter_size explicitly "
            "(output_size-only inference is not implemented)")

    def triple(v):
        return [int(v)] * 3 if isinstance(v, int) else [int(a) for a in v]

    stride, padding, dilation = (triple(stride), triple(padding),
                                 triple(dilation))
    filter_size = triple(filter_size)
    c_in = input.shape[1]
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[c_in, num_filters] + filter_size, dtype=dtype,
        is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": groups or 1},
    )
    pre_act = helper.append_bias_op(out, dim_start=1)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    """reference nn.py pool3d → pool_op.cc 3-D."""
    def triple(v):
        return [int(v)] * 3 if isinstance(v, int) else [int(a) for a in v]

    return _simple(
        "pool3d", {"X": input},
        {"pooling_type": pool_type, "ksize": triple(pool_size),
         "strides": triple(pool_stride), "paddings": triple(pool_padding),
         "global_pooling": global_pooling, "exclusive": exclusive})


def _adaptive_window(spatial, out_sizes, what):
    """Uniform window for adaptive pooling; the reference's ragged
    ceil/floor windows coincide with this exactly when each input extent
    divides its output extent — the static-shape TPU contract."""
    for s, o in zip(spatial, out_sizes):
        if int(s) % int(o):
            raise ValueError(
                "%s on TPU needs input extent %% output extent == 0 "
                "(static windows); got input %s for pool_size %s"
                % (what, list(spatial), list(out_sizes)))
    k = [int(s) // int(o) for s, o in zip(spatial, out_sizes)]
    return k


def adaptive_pool2d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    """reference nn.py adaptive_pool2d → pool_op adaptive attr;
    require_index routes to max_pool2d_with_index
    (pool_with_index_op.cc) and returns (out, flat-HW indices)."""

    def pair(v):
        return [int(v)] * 2 if isinstance(v, int) else [int(a) for a in v]

    if require_index:
        if pool_type != "max":
            raise ValueError("require_index=True only with pool_type='max'")
        k = _adaptive_window(input.shape[2:], pair(pool_size),
                             "adaptive_pool2d(require_index=True)")
        helper = LayerHelper("max_pool2d_with_index", **locals())
        out = helper.create_variable_for_type_inference(input.dtype)
        mask = helper.create_variable_for_type_inference("int32", True)
        helper.append_op(
            type="max_pool2d_with_index", inputs={"X": [input]},
            outputs={"Out": [out], "Mask": [mask]},
            attrs={"ksize": k, "strides": list(k), "paddings": [0, 0]},
        )
        return out, mask
    return _simple(
        "pool2d", {"X": input},
        {"pooling_type": pool_type, "ksize": pair(pool_size),
         "adaptive": True, "strides": [1, 1], "paddings": [0, 0]})


def adaptive_pool3d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    """reference nn.py adaptive_pool3d → pool_op adaptive attr;
    require_index routes to max_pool3d_with_index."""

    def triple(v):
        return [int(v)] * 3 if isinstance(v, int) else [int(a) for a in v]

    if require_index:
        if pool_type != "max":
            raise ValueError("require_index=True only with pool_type='max'")
        k = _adaptive_window(input.shape[2:], triple(pool_size),
                             "adaptive_pool3d(require_index=True)")
        helper = LayerHelper("max_pool3d_with_index", **locals())
        out = helper.create_variable_for_type_inference(input.dtype)
        mask = helper.create_variable_for_type_inference("int32", True)
        helper.append_op(
            type="max_pool3d_with_index", inputs={"X": [input]},
            outputs={"Out": [out], "Mask": [mask]},
            attrs={"ksize": k, "strides": list(k),
                   "paddings": [0, 0, 0]},
        )
        return out, mask
    return _simple(
        "pool3d", {"X": input},
        {"pooling_type": pool_type, "ksize": triple(pool_size),
         "adaptive": True, "strides": [1, 1, 1], "paddings": [0, 0, 0]})


# ---- sequence extras ----------------------------------------------------

def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None, seq_len=None):
    """reference nn.py sequence_conv → sequence_conv_op.h (padded
    [B,T,D] + seq_len)."""
    helper = LayerHelper("sequence_conv", **locals())
    dtype = helper.input_dtype()
    d = input.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[filter_size * d, num_filters],
        dtype=dtype, is_bias=False)
    start = (-int(filter_size // 2) if padding_start is None
             else int(padding_start))
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [input], "Filter": [w]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        type="sequence_conv", inputs=inputs, outputs={"Out": [out]},
        attrs={"contextLength": int(filter_size),
               "contextStart": start, "contextStride": int(filter_stride)},
    )
    pre_act = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(pre_act)


def sequence_expand_as(x, y, ref_len=None, name=None):
    """reference nn.py sequence_expand_as (padded: x [B,D] rows repeated
    to y's [B,T,...] time extent, masked by ref_len)."""
    return _simple("sequence_expand_as",
                   {"X": x, "Y": y, "RefLen": ref_len})


def sequence_reshape(input, new_dim):
    """reference nn.py sequence_reshape → sequence_reshape_op.h"""
    return _simple("sequence_reshape", {"X": input},
                   {"new_dim": int(new_dim)})


def sequence_scatter(input, index, updates, seq_len=None, name=None):
    """reference nn.py sequence_scatter → sequence_scatter_op.h"""
    return _simple("sequence_scatter",
                   {"X": input, "Ids": index, "Updates": updates,
                    "SeqLen": seq_len})


# ---- CTR / misc ---------------------------------------------------------

def continuous_value_model(input, cvm, use_cvm=True):
    """reference nn.py continuous_value_model → cvm_op.cc"""
    return _simple("cvm", {"X": input, "CVM": cvm},
                   {"use_cvm": bool(use_cvm)}, outs=("Y",))


def get_tensor_from_selected_rows(x, name=None):
    """reference nn.py get_tensor_from_selected_rows (identity on TPU:
    SelectedRows subsumed by dense scatter-add grads)."""
    return _simple("get_tensor_from_selected_rows", {"X": x})


def merge_selected_rows(x, name=None):
    """reference nn.py merge_selected_rows (identity on TPU)."""
    return _simple("merge_selected_rows", {"X": x})


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference nn.py py_func → py_func_op.cc.  `out` must be variables
    with static shapes (created via create_variable/data); backward_func
    is not supported (host grads break the jit boundary)."""
    if backward_func is not None:
        raise NotImplementedError(
            "py_func backward_func: host-side gradients are not "
            "representable under jit; compute grads in-graph instead")
    from ..ops import py_func_registry

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    specs = [(tuple(o.shape), o.dtype) for o in outs]
    fid = py_func_registry.register(func, specs)
    helper = LayerHelper("py_func")
    helper.append_op(
        type="py_func",
        inputs={"X": list(xs)},
        outputs={"Out": list(outs)},
        attrs={"func_id": fid},
    )
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """reference nn.py tree_conv → tree_conv_op.h (simplified continuous
    binary-tree aggregation)."""
    helper = LayerHelper("tree_conv", **locals())
    dtype = helper.input_dtype("nodes_vector")
    d = nodes_vector.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[d, output_size, 3], dtype=dtype,
        is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="tree_conv",
        inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                "Filter": [w]},
        outputs={"Out": [out]},
        attrs={"max_depth": int(max_depth)},
    )
    return out


def similarity_focus(input, axis, indexes, name=None):
    """reference nn.py similarity_focus → similarity_focus_op.h"""
    return _simple("similarity_focus", {"X": input},
                   {"axis": int(axis), "indexes": [int(i) for i in indexes]})


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=None,
                    deformable_groups=None, im2col_step=None,
                    param_attr=None, bias_attr=None, modulated=True,
                    name=None):
    """reference nn.py deformable_conv → deformable_conv_op (v2
    modulated; v1 with mask=None)."""
    helper = LayerHelper("deformable_conv", **locals())
    dtype = helper.input_dtype()

    def pair(v):
        return [int(v)] * 2 if isinstance(v, int) else [int(a) for a in v]

    fs = pair(filter_size)
    c_in = input.shape[1]
    g = groups or 1
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_filters, c_in // g] + fs,
        dtype=dtype, is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Offset": [offset], "Filter": [w]}
    if modulated and mask is not None:
        inputs["Mask"] = [mask]
    helper.append_op(
        type="deformable_conv", inputs=inputs,
        outputs={"Output": [out]},
        attrs={"strides": pair(stride), "paddings": pair(padding),
               "dilations": pair(dilation), "groups": g,
               "deformable_groups": deformable_groups or 1},
    )
    pre_act = helper.append_bias_op(out, dim_start=1)
    return helper.append_activation(pre_act)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=True,
                           name=None):
    """reference nn.py deformable_roi_pooling →
    deformable_psroi_pooling_op."""
    out_dim = input.shape[1] // (pooled_height * pooled_width) \
        if position_sensitive else input.shape[1]
    out, _ = _simple(
        "deformable_psroi_pooling",
        {"Input": input, "ROIs": rois,
         "Trans": None if no_trans else trans},
        {"spatial_scale": float(spatial_scale),
         "pooled_height": int(pooled_height),
         "pooled_width": int(pooled_width),
         "output_dim": int(out_dim),
         "trans_std": float(trans_std),
         "sample_per_part": int(sample_per_part),
         "no_trans": bool(no_trans)},
        outs=("Output", "TopCount"))
    return out
