"""Tensor creation/util layers (reference:
``python/paddle/fluid/layers/tensor.py``)."""

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer

__all__ = [
    "create_tensor",
    "create_global_var",
    "cast",
    "concat",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "ones_like",
    "zeros_like",
    "has_inf",
    "has_nan",
    "isfinite",
    "range",
    "linspace",
    "diag",
    "argmax",
    "argmin",
    "create_parameter",
    "reverse",
    "tensor_array_to_tensor",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", **locals())
    return helper.create_variable(
        name=helper.name, dtype=dtype, persistable=persistable
    )


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", **locals())
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable
    )
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast", **locals())
    from ..core import convert_np_dtype_to_dtype_

    dtype = convert_np_dtype_to_dtype_(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"in_dtype": x.dtype, "out_dtype": dtype},
    )
    return out


def concat(input, axis=0, name=None):
    from .nn import concat as _concat

    return _concat(input, axis, name)


def sums(input, out=None):
    helper = LayerHelper("sum", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(input)},
                     outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign", **locals())
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(
            type="assign", inputs={"X": [input]}, outputs={"Out": [output]}
        )
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                str(input.dtype)
            )
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output]},
            attrs={
                "shape": list(input.shape),
                "dtype": str(input.dtype),
                "values": input,
            },
        )
    else:
        raise TypeError("assign expects Variable or ndarray")
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={
            "shape": [int(s) for s in shape],
            "dtype": dtype,
            "value": float(value),
        },
        stop_gradient=True,
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": [int(s) for s in shape],
            "dtype": dtype,
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
        stop_gradient=True,
    )
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones_like(x, out=None):
    helper = LayerHelper("fill_any_like", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="fill_any_like", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"value": 1.0, "dtype": -1},
    )
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("fill_any_like", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="fill_any_like", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"value": 0.0, "dtype": -1},
    )
    return out


def has_inf(x):
    helper = LayerHelper("isinf", **locals())
    out = helper.create_variable_for_type_inference("bool", True)
    helper.append_op(type="isinf", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_nan(x):
    helper = LayerHelper("isnan", **locals())
    out = helper.create_variable_for_type_inference("bool", True)
    helper.append_op(type="isnan", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def isfinite(x):
    helper = LayerHelper("isfinite", **locals())
    out = helper.create_variable_for_type_inference("bool", True)
    helper.append_op(type="isfinite", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def range(start, end, step, dtype):
    helper = LayerHelper("range", **locals())
    attrs = {"dtype": dtype}
    inputs = {}
    # python scalars become static attrs (XLA needs a static length);
    # Variables are passed through and must be trace-time constants
    for key, val in (("start", start), ("end", end), ("step", step)):
        if isinstance(val, Variable):
            inputs[key.capitalize()] = [val]
        else:
            attrs[key] = float(val)
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op(
        type="range", inputs=inputs, outputs={"Out": [out]}, attrs=attrs,
    )
    return out


def linspace(start, stop, num, dtype):
    """Emit the linspace op (reference tensor.py:880: Start/Stop as
    1-element tensors, Num pinned static via the num attr for XLA)."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("linspace", **locals())
    start_t = start if isinstance(start, Variable) else fill_constant(
        [1], dtype, float(start))
    stop_t = stop if isinstance(stop, Variable) else fill_constant(
        [1], dtype, float(stop))
    inputs = {"Start": [start_t], "Stop": [stop_t]}
    attrs = {}
    if isinstance(num, Variable):
        # reference API admits a Variable num; XLA needs it concrete at
        # lowering (the op resolves it or raises a targeted error)
        inputs["Num"] = [num]
    else:
        attrs["num"] = int(num)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="linspace", inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def diag(diagonal):
    """reference layers/tensor.py diag → diag_op.cc (square matrix from a
    1-D diagonal); numpy input short-circuits to a constant."""
    if isinstance(diagonal, np.ndarray):
        return assign(np.diag(diagonal))
    helper = LayerHelper("diag", **locals())
    out = helper.create_variable_for_type_inference(diagonal.dtype)
    helper.append_op(
        type="diag", inputs={"Diagonal": [diagonal]},
        outputs={"Out": [out]},
    )
    return out


def argmax(x, axis=0):
    from .nn import argmax as _argmax

    return _argmax(x, axis)


def argmin(x, axis=0):
    from .nn import argmin as _argmin

    return _argmin(x, axis)


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference tensor.py create_parameter: a raw trainable parameter."""
    from ..layer_helper import LayerHelper
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter")
    a = ParamAttr._to_attr(attr)
    if name is not None and a.name is None:
        a.name = name
    if default_initializer is not None:
        a._set_default_initializer(default_initializer)
    return helper.create_parameter(a, list(shape), dtype, is_bias=is_bias)


def reverse(x, axis):
    """reference tensor.py reverse → reverse op."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="reverse", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": [axis] if isinstance(axis, int) else list(axis)},
    )
    return out


def tensor_array_to_tensor(input, axis=1, name=None):
    """reference tensor.py tensor_array_to_tensor: stack/concat a
    LoDTensorArray back into one tensor along `axis`."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("tensor_array_to_tensor")
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference("int32", True)
    helper.append_op(
        type="tensor_array_to_tensor",
        inputs={"X": [input]},
        outputs={"Out": [out], "OutIndex": [idx]},
        attrs={"axis": int(axis)},
    )
    return out, idx
