"""Beam-search layers (reference: ``python/paddle/fluid/layers/nn.py``
``beam_search``/``beam_search_decode``, backed by
``operators/beam_search_op.cc``).  Dense [B, K] beam layout — see
ops/beam_search.py for the static-shape redesign notes."""

from ..layer_helper import LayerHelper

__all__ = ["beam_search", "beam_search_decode"]


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """One beam expansion step over dense [B, K] beams.

    ``scores`` must be [B, K, V]; pass ``is_accumulated=False`` when they
    are per-step log-probs to be added to ``pre_scores``.  Returns
    (selected_ids, selected_scores[, parent_idx]) each [B, K].
    """
    helper = LayerHelper("beam_search", **locals())
    sel_ids = helper.create_variable_for_type_inference("int32")
    sel_scores = helper.create_variable_for_type_inference(scores.dtype)
    parent_idx = helper.create_variable_for_type_inference("int32")
    inputs = {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
              "scores": [scores]}
    if ids is not None:
        inputs["ids"] = [ids]
    helper.append_op(
        type="beam_search",
        inputs=inputs,
        outputs={"selected_ids": [sel_ids],
                 "selected_scores": [sel_scores],
                 "parent_idx": [parent_idx]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id),
               "level": int(level), "is_accumulated": bool(is_accumulated)},
    )
    sel_ids.stop_gradient = True
    sel_scores.stop_gradient = True
    parent_idx.stop_gradient = True
    if return_parent_idx:
        return sel_ids, sel_scores, parent_idx
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, parent_idx, beam_size, end_id, name=None):
    """Backtrace beam arrays into sentences.

    ``ids``/``scores``/``parent_idx`` are tensor arrays written once per
    step (see layers.array_write).  Returns (sentence_ids [B, K, T],
    sentence_scores [B, K]).
    """
    helper = LayerHelper("beam_search_decode", **locals())
    sent_ids = helper.create_variable_for_type_inference("int32")
    sent_scores = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Scores": [scores], "ParentIdx": [parent_idx]},
        outputs={"SentenceIds": [sent_ids], "SentenceScores": [sent_scores]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id)},
    )
    sent_ids.stop_gradient = True
    sent_scores.stop_gradient = True
    return sent_ids, sent_scores
