"""Autoregressive decoding layers: KV cache, flash-decode attention,
sampling, and the recompile-free ``decode_loop``.

The reference's generation stack (``fluid.layers.beam_search`` /
``beam_search_decode`` and ``contrib.decoder.beam_search_decoder``)
rebuilds a per-step graph over a growing sequence; the TPU-native
formulation here keeps every shape static — a ring-buffer KV cache
(``create_kv_cache`` + ``kv_cache_write``) with an integer cursor, a
single-query flash-decode attention read, and a ``while_op`` loop whose
body lowers to ONE jaxpr for the whole generation.  The jit cache holds
one entry per (batch, prompt-bucket) regardless of generated length,
and the loop is grad-free end to end so the executor never takes the
unbounded-while host-probing path (the PR-10 zero-sync certificate
holds over the decode hot loop).

``beam_search.py`` remains the classic path; the sampling ops here
(greedy / temperature / top-k / top-p) are the modern serving path.
"""

from ..layer_helper import LayerHelper
from . import tensor as tensor_layers
from . import control_flow as cf_layers
from . import nn as nn_layers

__all__ = [
    "create_kv_cache", "kv_cache_write", "kv_cache_prefill",
    "flash_decode", "create_paged_kv_cache", "paged_kv_cache_write",
    "paged_kv_cache_prefill", "paged_flash_decode", "top_k_sampling",
    "top_p_sampling", "greedy_sampling", "sampling", "decode_loop",
]


def create_kv_cache(batch, heads, max_len, head_dim, dtype="float32",
                    name=None):
    """A zero-initialized ring-buffer cache var [batch, heads, max_len,
    head_dim] with a STATIC max shape — the device-resident buffer the
    decode loop writes through its cursor.  ``batch`` may be -1 (batch
    dim resolved by the feed bucket)."""
    shape = [batch, heads, max_len, head_dim]
    if batch == -1:
        # materialized full-shape per feed bucket by fill_constant's
        # batch-size-like expansion path
        raise ValueError(
            "create_kv_cache needs a static batch (the serving bucket "
            "size); got -1")
    return tensor_layers.fill_constant(shape, dtype, 0.0)


def _append(op_type, inputs, outputs, attrs):
    helper = LayerHelper(op_type)
    helper.append_op(type=op_type, inputs=inputs, outputs=outputs,
                     attrs=attrs)


def kv_cache_write(cache, x, cursor, per_row=False, in_place=True,
                   name=None):
    """Write this step's K (or V) [B, H, D] into ``cache`` at ``cursor``
    (ring semantics).  With ``in_place`` (default) the op writes the
    cache var itself — inside a ``While`` body that is what makes the
    cache a loop carry, exactly like ``increment``'s counter idiom."""
    helper = LayerHelper("kv_cache_write", **locals())
    out = cache if in_place else \
        helper.create_variable_for_type_inference(cache.dtype)
    helper.append_op(
        type="kv_cache_write",
        inputs={"Cache": [cache], "X": [x], "Cursor": [cursor]},
        outputs={"Out": [out]},
        attrs={"per_row": bool(per_row)},
    )
    return out


def kv_cache_prefill(cache, x, slot=None, in_place=True, name=None):
    """Bulk-write a prompt's K/V [B, H, L, D] into cache rows [0, L).
    ``slot`` ([1] int32 var) routes a batch-1 prefill into that cache
    row — the serving path that admits a request into a free slot."""
    helper = LayerHelper("kv_cache_prefill", **locals())
    out = cache if in_place else \
        helper.create_variable_for_type_inference(cache.dtype)
    inputs = {"Cache": [cache], "X": [x]}
    if slot is not None:
        inputs["Slot"] = [slot]
    helper.append_op(type="kv_cache_prefill", inputs=inputs,
                     outputs={"Out": [out]}, attrs={})
    return out


def flash_decode(q, k_cache, v_cache, cursor, sm_scale=None,
                 per_row=False, name=None):
    """Single-query attention [B, H, D] against the ring cache, masked
    to ``cursor`` valid entries (Pallas flash-decode kernel on TPU, XLA
    composite elsewhere — ops/pallas/flash_decode.py)."""
    helper = LayerHelper("flash_decode", **locals())
    out = helper.create_variable_for_type_inference(q.dtype)
    attrs = {"per_row": bool(per_row)}
    if sm_scale is not None:
        attrs["sm_scale"] = float(sm_scale)
    helper.append_op(
        type="flash_decode_attention",
        inputs={"Q": [q], "KCache": [k_cache], "VCache": [v_cache],
                "Cursor": [cursor]},
        outputs={"Out": [out]},
        attrs=attrs,
    )
    return out


def create_paged_kv_cache(num_blocks, heads, block_len, head_dim,
                          dtype="float32", name=None):
    """A zero-initialized paged KV pool ``[num_blocks, heads,
    block_len, head_dim]`` — HBM carved into fixed-size blocks that a
    free-list hands to requests (serving/paging.py); block tables route
    each stream's reads/writes into its owned blocks."""
    shape = [num_blocks, heads, block_len, head_dim]
    return tensor_layers.fill_constant(shape, dtype, 0.0)


def paged_kv_cache_write(cache, x, cursor, table, per_row=True,
                         in_place=True, name=None):
    """Write this step's K (or V) ``[S, H, D]`` into the paged pool at
    each stream's cursor, routed through its block-table row (``-1``
    entries drop the write — inactive streams leave the pool
    untouched)."""
    helper = LayerHelper("paged_kv_cache_write", **locals())
    out = cache if in_place else \
        helper.create_variable_for_type_inference(cache.dtype)
    helper.append_op(
        type="paged_kv_cache_write",
        inputs={"Cache": [cache], "X": [x], "Cursor": [cursor],
                "BlockTable": [table]},
        outputs={"Out": [out]},
        attrs={"per_row": bool(per_row)},
    )
    return out


def paged_kv_cache_prefill(cache, x, length, table, in_place=True,
                           name=None):
    """Bulk-write a prompt's K/V ``[1, H, L, D]`` into the blocks its
    table owns; padded positions ``>= length`` are dropped."""
    helper = LayerHelper("paged_kv_cache_prefill", **locals())
    out = cache if in_place else \
        helper.create_variable_for_type_inference(cache.dtype)
    helper.append_op(
        type="paged_kv_cache_prefill",
        inputs={"Cache": [cache], "X": [x], "Len": [length],
                "BlockTable": [table]},
        outputs={"Out": [out]}, attrs={},
    )
    return out


def paged_flash_decode(q, k_cache, v_cache, cursor, table,
                       sm_scale=None, per_row=True, name=None):
    """Single-query attention ``[S, H, D]`` through the block table,
    masked to ``cursor`` valid entries per stream (Pallas paged kernel
    on TPU, gather + ring-oracle composite elsewhere —
    ops/pallas/paged_flash_decode.py).  Rows are independent, so the
    speculative verify feeds ``k+1`` rows per stream with graduated
    cursors."""
    helper = LayerHelper("paged_flash_decode", **locals())
    out = helper.create_variable_for_type_inference(q.dtype)
    attrs = {"per_row": bool(per_row)}
    if sm_scale is not None:
        attrs["sm_scale"] = float(sm_scale)
    helper.append_op(
        type="paged_flash_decode_attention",
        inputs={"Q": [q], "KCache": [k_cache], "VCache": [v_cache],
                "Cursor": [cursor], "BlockTable": [table]},
        outputs={"Out": [out]},
        attrs=attrs,
    )
    return out


def _sampling_op(op_type, logits, attrs, step, name):
    helper = LayerHelper(op_type, logits=logits, name=name)
    out = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [logits]}
    if step is not None:
        inputs["Step"] = [step]
    helper.append_op(type=op_type, inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def top_k_sampling(logits, k=1, temperature=1.0, seed=0, step=None,
                   name=None):
    """Token ids [B] sampled from the top-k of logits [B, V]; ``k=1``
    or ``temperature<=0`` is greedy argmax.  ``step`` (the loop index
    var) decorrelates draws across decode steps."""
    return _sampling_op(
        "top_k_sampling", logits,
        {"k": int(k), "temperature": float(temperature),
         "seed": int(seed)}, step, name)


def top_p_sampling(logits, p=0.9, temperature=1.0, seed=0, step=None,
                   name=None):
    """Nucleus sampling over logits [B, V]: smallest descending-softmax
    prefix reaching mass ``p`` (head token always kept)."""
    return _sampling_op(
        "top_p_sampling", logits,
        {"p": float(p), "temperature": float(temperature),
         "seed": int(seed)}, step, name)


def greedy_sampling(logits, name=None):
    """Argmax token ids [B] — the deterministic decode path."""
    return top_k_sampling(logits, k=1, temperature=0.0, name=name)


def sampling(logits, strategy="greedy", k=8, p=0.9, temperature=1.0,
             seed=0, step=None, name=None):
    """Dispatch to greedy / top-k / top-p by name (the decode_loop and
    serving tenant-config entry point)."""
    if strategy == "greedy":
        return greedy_sampling(logits, name=name)
    if strategy == "top_k":
        return top_k_sampling(logits, k=k, temperature=temperature,
                              seed=seed, step=step, name=name)
    if strategy == "top_p":
        return top_p_sampling(logits, p=p, temperature=temperature,
                              seed=seed, step=step, name=name)
    raise ValueError("unknown sampling strategy %r "
                     "(greedy|top_k|top_p)" % (strategy,))


def decode_loop(step_fn, first_ids, prompt_len, max_new_tokens,
                eos_id=None, strategy="greedy", k=8, p=0.9,
                temperature=1.0, seed=0, name=None):
    """The recompile-free generation loop.

    ``step_fn(cur_ids, cursor, step) -> logits`` builds ONE decode step:
    embed ``cur_ids`` [B] at position ``cursor`` [1], write K/V through
    :func:`kv_cache_write`, attend with :func:`flash_decode`, and return
    next-token logits [B, V].  ``first_ids`` [B] is the first generated
    token (sampled from the prefill's last-position logits);
    ``prompt_len`` [1] int32 is the cursor start.

    Returns ``(tokens, gen_len)``: tokens [B, max_new_tokens] int32,
    gen_len [B] int32.  A row that hits eos keeps emitting eos until
    every row is done; positions past the loop's early exit keep the
    initial zero fill — slice each row with ``gen_len``.  The body
    carries only static-shape state (ring caches via ``in_place``
    writes, the [1] counters, the fixed-capacity token array), so the
    whole generation is one jit-cache entry; with ``eos_id`` the loop
    exits early once every row has finished — without changing shapes
    or adding a host sync.
    """
    layers = _fluid_layers()
    max_new_tokens = int(max_new_tokens)
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")

    i = layers.fill_constant([1], "int32", 1)
    limit = layers.fill_constant([1], "int32", max_new_tokens)
    cursor = layers.assign(prompt_len)  # don't mutate the feed var
    cur = layers.assign(first_ids)
    ones = layers.cast(layers.equal(cur, cur), "int32")  # [B] of 1
    gen_len = layers.assign(ones)
    arr = layers.array_write(
        layers.unsqueeze(cur, [1]),
        layers.fill_constant([1], "int32", 0),
        capacity=max_new_tokens)

    if eos_id is not None:
        eos_c = layers.fill_constant([1], "int32", int(eos_id))
        finished = layers.equal(cur, eos_c)
        running = layers.logical_not(layers.reduce_all(finished))
        cond = layers.logical_and(layers.less_than(i, limit), running)
    else:
        finished = None
        cond = layers.less_than(i, limit)

    w = cf_layers.While(cond, max_trip_count=max_new_tokens)
    with w.block():
        logits = step_fn(cur, cursor, i)
        nxt = sampling(logits, strategy=strategy, k=k, p=p,
                       temperature=temperature, seed=seed, step=i)
        if eos_id is not None:
            # rows already finished keep emitting eos; live rows count
            # this token
            nxt = layers.where(finished, layers.elementwise_mul(
                ones, eos_c), nxt)
            live = layers.cast(layers.logical_not(finished), "int32")
            layers.assign(layers.elementwise_add(gen_len, live),
                          output=gen_len)
            layers.assign(
                layers.logical_or(finished, layers.equal(nxt, eos_c)),
                output=finished)
        layers.array_write(layers.unsqueeze(nxt, [1]), i, array=arr)
        layers.assign(nxt, output=cur)
        layers.increment(i, value=1, in_place=True)
        layers.increment(cursor, value=1, in_place=True)
        if eos_id is not None:
            running = layers.logical_not(layers.reduce_all(finished))
            layers.assign(
                layers.logical_and(layers.less_than(i, limit), running),
                output=cond)
        else:
            layers.less_than(i, limit, cond=cond)

    tokens, _ = tensor_layers.tensor_array_to_tensor(arr, axis=1)
    return tokens, gen_len


def _fluid_layers():
    """The assembled layers namespace (avoids import cycles: this module
    is imported by ``layers/__init__`` before the star-imports run)."""
    from .. import layers as L

    return L
