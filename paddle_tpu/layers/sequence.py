"""Sequence layers over padded batches (reference: these live in
``python/paddle/fluid/layers/nn.py`` as LoD-aware sequence_* functions and
``dynamic_lstm``/``dynamic_gru``).

Representation change (SURVEY.md §5): instead of LoD offsets carried on the
tensor, sequence layers accept an optional ``seq_len`` Variable ([B] ints).
Omitted seq_len = all rows full length."""

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = [
    "sequence_pool",
    "sequence_softmax",
    "sequence_reverse",
    "sequence_concat",
    "sequence_expand",
    "sequence_pad",
    "sequence_unpad",
    "sequence_mask",
    "sequence_slice",
    "sequence_enumerate",
    "sequence_first_step",
    "sequence_last_step",
    "dynamic_lstm",
    "dynamic_gru",
]


def _seq_op(op_type, helper_name, x, seq_len=None, out_dtype=None,
            extra_inputs=None, attrs=None, outputs_spec=None):
    helper = LayerHelper(helper_name)
    out = helper.create_variable_for_type_inference(out_dtype or x.dtype)
    inputs = {"X": [x]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    if extra_inputs:
        inputs.update(extra_inputs)
    outs = {"Out": [out]}
    extra_outs = {}
    if outputs_spec:
        for slot, dtype in outputs_spec.items():
            extra_outs[slot] = [
                helper.create_variable_for_type_inference(dtype, True)
            ]
        outs.update(extra_outs)
    helper.append_op(type=op_type, inputs=inputs, outputs=outs,
                     attrs=attrs or {})
    return out


def sequence_pool(input, pool_type, is_test=False, seq_len=None):
    return _seq_op(
        "sequence_pool", "sequence_pool", input, seq_len,
        attrs={"pooltype": pool_type.upper()},
        outputs_spec={"MaxIndex": "int32"},
    )


def sequence_first_step(input, seq_len=None):
    return sequence_pool(input, "first", seq_len=seq_len)


def sequence_last_step(input, seq_len=None):
    return sequence_pool(input, "last", seq_len=seq_len)


def sequence_softmax(input, use_cudnn=False, name=None, seq_len=None):
    return _seq_op("sequence_softmax", "sequence_softmax", input, seq_len)


def sequence_reverse(x, name=None, seq_len=None):
    helper = LayerHelper("sequence_reverse", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(type="sequence_reverse", inputs=inputs,
                     outputs={"Y": [out]})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", **locals())
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sequence_concat", inputs={"X": list(input)},
                     outputs={"Out": [out]})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand", inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]}, attrs={"ref_level": ref_level},
    )
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None, seq_len=None):
    helper = LayerHelper("sequence_pad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int64", True)
    inputs = {"X": [x], "PadValue": [pad_value]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        type="sequence_pad", inputs=inputs,
        outputs={"Out": [out], "Length": [length]},
    )
    return out, length


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_unpad", inputs={"X": [x], "Length": [length]},
        outputs={"Out": [out]},
    )
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", **locals())
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op(
        type="sequence_mask", inputs={"X": [x]}, outputs={"Y": [out]},
        attrs={"maxlen": int(maxlen) if maxlen else -1, "out_dtype": dtype},
    )
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out]},
    )
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate", **locals())
    out = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(
        type="sequence_enumerate", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"win_size": win_size, "pad_value": pad_value},
    )
    return out


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 seq_len=None):
    """LSTM over padded [B, T, 4*hidden] pre-projected input (reference
    nn.py:427 dynamic_lstm over LoD input; input = fc(x, 4*hidden) as
    there).  size = 4 * hidden.  ``use_peepholes`` defaults True exactly
    like the reference: the bias then carries [1, 7*hidden] with the
    trailing [W_ic, W_fc, W_oc] peephole weights."""
    assert size % 4 == 0
    hidden = size // 4
    if use_peepholes and bias_attr is False:
        raise ValueError(
            "dynamic_lstm(use_peepholes=True) — the reference default — "
            "stores the W_ic/W_fc/W_oc peephole weights in the bias; "
            "bias_attr must not be False (or pass use_peepholes=False)")
    helper = LayerHelper("dynamic_lstm", **locals())
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden, 4 * hidden], dtype=dtype
    )
    bias_width = 7 * hidden if use_peepholes else 4 * hidden
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, bias_width], dtype=dtype,
        is_bias=True,
    )
    hidden_out = helper.create_variable_for_type_inference(dtype)
    cell_out = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        type="dynamic_lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden_out], "Cell": [cell_out]},
        attrs={
            "use_peepholes": bool(use_peepholes),
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    return hidden_out, cell_out


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, name=None,
                seq_len=None):
    """GRU over padded [B, T, 3*size] pre-projected input (reference nn.py
    dynamic_gru)."""
    helper = LayerHelper("dynamic_gru", **locals())
    dtype = input.dtype
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True
    )
    hidden_out = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        type="dynamic_gru",
        inputs=inputs,
        outputs={"Hidden": [hidden_out]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    return hidden_out
