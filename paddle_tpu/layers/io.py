"""Data-layer front-end (reference: ``python/paddle/fluid/layers/io.py``)."""

from ..framework import default_main_program, default_startup_program
from .. import core

__all__ = [
    "data",
    "load",
    "py_reader",
    "create_py_reader_by_data",
    "read_file",
    "double_buffer",
    "batch",
    "shuffle",
    "random_data_generator",
    "Preprocessor",
    "open_files",
]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         type=core.VarDesc.VarType.LOD_TENSOR, stop_gradient=True):
    """Declare an input variable fed at run time (reference io.py `data`).
    With append_batch_size, a leading -1 batch dim is added."""
    helper_block = default_main_program().current_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    var = helper_block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        is_data=True,
        need_check_feed=True,
    )
    # mirror into the startup program like the reference so either program
    # can resolve the var
    sb = default_startup_program().current_block()
    if not sb.has_var(name):
        sb.create_var(
            name=name, shape=shape, dtype=dtype, lod_level=lod_level,
            stop_gradient=stop_gradient, is_data=True,
        )
    return var


def py_reader(capacity=64, shapes=None, dtypes=None, lod_levels=None,
              name=None, use_double_buffer=True):
    """reference layers/io.py py_reader (graph-side queue reader):
    creates the data vars and returns a PyReader bound to them; feed
    vars come from ``read_file`` and batches stream via the reader's
    decorate_* + iteration (the TPU path feeds per step instead of a
    graph-side read op)."""
    from .. import unique_name
    from ..reader import PyReader

    shapes = shapes or []
    dtypes = dtypes or ["float32"] * len(shapes)
    feed_vars = []
    for i, (sh, dt) in enumerate(zip(shapes, dtypes)):
        nm = unique_name.generate((name or "py_reader") + "_slot%d" % i)
        feed_vars.append(data(nm, shape=list(sh)[1:], dtype=dt))
    reader = PyReader(feed_list=feed_vars, capacity=capacity,
                      use_double_buffer=use_double_buffer, iterable=True)
    reader._py_reader_vars = feed_vars
    return reader


def create_py_reader_by_data(capacity=64, feed_list=None, name=None,
                             use_double_buffer=True):
    """reference layers/io.py create_py_reader_by_data."""
    from ..reader import PyReader

    reader = PyReader(feed_list=feed_list or [], capacity=capacity,
                      use_double_buffer=use_double_buffer, iterable=True)
    reader._py_reader_vars = list(feed_list or [])
    return reader


def read_file(reader):
    """reference layers/io.py read_file: yields the reader's data vars
    (the graph-side read op is subsumed — feeds stream per step)."""
    vs = getattr(reader, "_py_reader_vars", None)
    if vs is None:
        raise ValueError("read_file expects a py_reader-created reader")
    return vs[0] if len(vs) == 1 else list(vs)


def double_buffer(reader, place=None, name=None):
    """reference layers/io.py double_buffer: stage the reader's batches
    on DEVICE from a background thread (depth 2, env
    ``PADDLE_TPU_PIPELINE_DEPTH``) so H2D transfer of the next batch
    overlaps the async-dispatched current step — the role the
    reference's double-buffer queue + read op played.  ``place`` is
    accepted for API parity (placement is the default device)."""
    from .. import reader_decorators as rd

    return rd.device_buffered(reader)


def batch(reader, batch_size):
    """reference layers/io.py batch → reader-decorator composition."""
    from .. import reader_decorators as rd

    return rd.batch(reader, batch_size)


def shuffle(reader, buffer_size):
    """reference layers/io.py shuffle → reader-decorator composition."""
    from .. import reader_decorators as rd

    return rd.shuffle(reader, buffer_size)


def random_data_generator(low, high, shapes, lod_levels=None, for_parallel=True):
    """reference layers/io.py random_data_generator (uniform random
    reader, used by tests): returns a reader-creator yielding random
    tuples with the given shapes."""
    import numpy as np

    def reader():
        rng = np.random.RandomState(0)
        while True:
            yield tuple(
                rng.uniform(low, high, size=sh).astype("float32")
                for sh in shapes)

    return reader


class Preprocessor:
    """reference layers/io.py Preprocessor: user-defined transform over
    reader outputs; on TPU the transform runs host-side in the reader
    pipeline."""

    def __init__(self, reader, name=None):
        self.underlying = reader
        self._inputs = None
        self._outputs = None
        self._fn = None

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def guard():
            yield self

        return guard()

    def inputs(self):
        return self._inputs

    def outputs(self, *outs):
        self._outputs = outs


def open_files(filenames=None, shapes=None, lod_levels=None, dtypes=None,
               thread_num=None, buffer_size=None, pass_num=1,
               is_test=None):
    """reference layers/io.py open_files (RecordIO file readers): use
    paddle_tpu.recordio_writer + native scanner via datasets/readers
    instead; kept as explicit guidance."""
    raise NotImplementedError(
        "open_files: graph-side RecordIO readers are replaced by the "
        "host pipeline — read with native.recordio scanner + "
        "reader_decorators, then feed via PyReader")


def load(out, file_path, load_as_fp16=None):
    """Append an in-graph ``load`` op targeting `out` (reference
    ``layers/io.py:1269``; executed host-side by the Executor's save/load
    program path, ``ops/io_ops.py``)."""
    attrs = {"file_path": file_path}
    if load_as_fp16 is not None:
        attrs["load_as_fp16"] = load_as_fp16
    out.block.append_op(
        type="load", inputs={}, outputs={"Out": [out]}, attrs=attrs)
