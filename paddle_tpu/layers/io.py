"""Data-layer front-end (reference: ``python/paddle/fluid/layers/io.py``)."""

from ..framework import default_main_program, default_startup_program
from .. import core

__all__ = ["data"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         type=core.VarDesc.VarType.LOD_TENSOR, stop_gradient=True):
    """Declare an input variable fed at run time (reference io.py `data`).
    With append_batch_size, a leading -1 batch dim is added."""
    helper_block = default_main_program().current_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    var = helper_block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        is_data=True,
        need_check_feed=True,
    )
    # mirror into the startup program like the reference so either program
    # can resolve the var
    sb = default_startup_program().current_block()
    if not sb.has_var(name):
        sb.create_var(
            name=name, shape=shape, dtype=dtype, lod_level=lod_level,
            stop_gradient=stop_gradient, is_data=True,
        )
    return var
