"""Layers DSL (reference: ``python/paddle/fluid/layers/``)."""

from . import nn
from . import nn_extra
from . import nn_extra2
from . import io
from . import layer_function_generator
from . import tensor
from . import ops
from . import control_flow
from . import sequence
from . import metric_op
from . import detection
from . import detection_extra
from . import beam
from . import decode
from . import learning_rate_scheduler
from . import collective
from . import math_op_patch  # noqa: F401  (Variable operator overloads)

from .nn import *  # noqa: F401,F403
from .nn_extra import *  # noqa: F401,F403
from .nn_extra2 import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
from .layer_function_generator import (  # noqa: F401
    deprecated, generate_layer_fn, generate_activation_fn, autodoc,
    templatedoc)
from .tensor import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .metric_op import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .detection_extra import *  # noqa: F401,F403
from .beam import *  # noqa: F401,F403
from .decode import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403

__all__ = (
    nn.__all__
    + nn_extra.__all__
    + nn_extra2.__all__
    + io.__all__
    + tensor.__all__
    + ops.__all__
    + control_flow.__all__
    + sequence.__all__
    + metric_op.__all__
    + detection.__all__
    + detection_extra.__all__
    + beam.__all__
    + decode.__all__
    + learning_rate_scheduler.__all__
)
