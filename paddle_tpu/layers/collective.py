"""Program-level collective layers (reference:
``python/paddle/fluid/layers/collective.py`` `_allreduce:19`,
`_broadcast:52`; ops in ``paddle/fluid/operators/collective/``).

These exist for transpiler-parity: programs that explicitly insert
collectives still lower correctly.  The lowerings (ops/collective.py) emit
``lax.psum``-family primitives when the executor runs under a mesh axis
(shard_map), and are identity on a single device — GSPMD inserts the actual
ICI/DCN collectives."""

from ..layer_helper import LayerHelper

__all__ = ["_allreduce", "_broadcast", "_c_allgather", "_c_reducescatter"]


def _allreduce(x, out=None, reduce_type="sum", sync_mode=False):
    helper = LayerHelper("allreduce", **locals())
    if out is None:
        out = x
    helper.append_op(
        type="c_allreduce_" + reduce_type,
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"ring_id": 0, "use_calc_stream": not sync_mode},
    )
    return out


def _broadcast(x, root, sync_mode=False):
    helper = LayerHelper("broadcast", **locals())
    helper.append_op(
        type="c_broadcast",
        inputs={"X": [x]},
        outputs={"Out": [x]},
        attrs={"root": root, "ring_id": 0, "use_calc_stream": not sync_mode},
    )
    return x


def _c_allgather(x, nranks, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_allgather", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="c_allgather",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"nranks": nranks, "ring_id": ring_id,
               "use_calc_stream": use_calc_stream},
    )
    return out


def _c_reducescatter(x, nranks, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_reducescatter", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="c_reducescatter",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"nranks": nranks, "ring_id": ring_id,
               "use_calc_stream": use_calc_stream},
    )
    return out
