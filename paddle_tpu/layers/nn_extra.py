"""Second wave of the layers.nn surface (reference
``python/paddle/fluid/layers/nn.py``) — vision rearrangements, extra
losses, samplers, norms, and compositions.  Each function cites its
reference op; lowerings live in ops/{vision,losses,nn}.py."""

from ..framework import Variable
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from ..initializer import ConstantInitializer, NormalInitializer

__all__ = [
    "selu", "maxout", "multiplex", "crop", "pad_constant_like",
    "random_crop", "sampling_id", "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like", "gaussian_random",
    "add_position_encoding", "hash", "data_norm", "spectral_norm",
    "row_conv", "pixel_shuffle", "shuffle_channel", "space_to_depth",
    "temporal_shift", "affine_channel", "fsp_matrix", "grid_sampler",
    "affine_grid", "roi_pool", "psroi_pool", "unfold", "lrn",
    "log_loss", "kldiv_loss", "rank_loss", "margin_rank_loss", "bpr_loss",
    "teacher_student_sigmoid_loss", "mean_iou", "bilinear_tensor_product",
    "dice_loss", "npair_loss", "rank", "sum", "image_resize_short",
    "autoincreased_step_counter", "reduce_all", "reduce_any",
    "elementwise_mod", "elementwise_floordiv", "im2sequence",
]


def _simple(op_type, inputs, attrs=None, out_dtype=None, outs=("Out",),
            stop_gradient=False):
    helper = LayerHelper(op_type)
    dtype = out_dtype
    if dtype is None:
        for v in inputs.values():
            vs = v if isinstance(v, (list, tuple)) else [v]
            if vs and isinstance(vs[0], Variable):
                dtype = vs[0].dtype
                break
    outputs = {}
    ret = []
    for slot in outs:
        ov = helper.create_variable_for_type_inference(dtype, stop_gradient)
        outputs[slot] = [ov]
        ret.append(ov)
    helper.append_op(
        type=op_type,
        inputs={k: (list(v) if isinstance(v, (list, tuple)) else [v])
                for k, v in inputs.items() if v is not None},
        outputs=outputs,
        attrs=attrs or {},
    )
    return ret[0] if len(ret) == 1 else tuple(ret)


# ---- activations / selection -------------------------------------------

def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    """reference nn.py selu → selu_op.cc"""
    return _simple("selu", {"X": x}, {"scale": scale, "alpha": alpha})


def maxout(x, groups, name=None):
    """reference nn.py maxout → maxout_op.cc (NCHW)"""
    return _simple("maxout", {"X": x}, {"groups": groups})


def multiplex(inputs, index):
    """reference nn.py multiplex → multiplex_op.cc"""
    return _simple("multiplex", {"X": list(inputs), "Ids": index})


# ---- crops / pads / random ---------------------------------------------

def crop(x, shape=None, offsets=None, name=None):
    """reference nn.py crop → crop_op.cc; static shape/offsets or a Y
    template variable for the target shape."""
    attrs = {}
    ins = {"X": x}
    if isinstance(shape, Variable):
        ins["Y"] = shape
    else:
        attrs["shape"] = [int(s) for s in shape]
    if isinstance(offsets, Variable):
        ins["Offsets"] = offsets
    elif offsets is not None:
        attrs["offsets"] = [int(o) for o in offsets]
    return _simple("crop", ins, attrs)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """reference nn.py pad_constant_like → pad_constant_like_op.cc"""
    return _simple("pad_constant_like", {"X": x, "Y": y},
                   {"pad_value": float(pad_value)})


def random_crop(x, shape, seed=None):
    """reference nn.py random_crop → random_crop_op.h"""
    out, _ = _simple("random_crop", {"X": x},
                     {"shape": [int(s) for s in shape]},
                     outs=("Out", "SeedOut"))
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    """reference nn.py sampling_id → sampling_id_op.cc"""
    return _simple("sampling_id", {"X": x}, {"seed": seed},
                   out_dtype="int64", stop_gradient=True)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    """reference nn.py → uniform_random_batch_size_like_op.cc"""
    return _simple(
        "uniform_random_batch_size_like", {"Input": input},
        {"shape": [int(s) for s in shape], "input_dim_idx": input_dim_idx,
         "output_dim_idx": output_dim_idx, "min": min, "max": max,
         "seed": seed, "dtype": dtype},
        out_dtype=dtype, stop_gradient=True)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    """reference nn.py → gaussian_random_batch_size_like_op.cc"""
    return _simple(
        "gaussian_random_batch_size_like", {"Input": input},
        {"shape": [int(s) for s in shape], "input_dim_idx": input_dim_idx,
         "output_dim_idx": output_dim_idx, "mean": mean, "std": std,
         "seed": seed, "dtype": dtype},
        out_dtype=dtype, stop_gradient=True)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    """reference nn.py gaussian_random → gaussian_random_op.cc"""
    return _simple(
        "gaussian_random", {},
        {"shape": [int(s) for s in shape], "mean": mean, "std": std,
         "seed": seed, "dtype": dtype},
        out_dtype=dtype, stop_gradient=True)


# ---- positional / hashing / norms --------------------------------------

def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """reference nn.py add_position_encoding → add_position_encoding_op.h"""
    return _simple("add_position_encoding", {"X": input},
                   {"alpha": float(alpha), "beta": float(beta)})


def hash(input, hash_size, num_hash=1, name=None):
    """reference nn.py hash → hash_op.h (xxhash there; splitmix-style
    mix here — deterministic but not bit-identical across frameworks)."""
    return _simple("hash", {"X": input},
                   {"mod_by": int(hash_size), "num_hash": int(num_hash)},
                   out_dtype="int64", stop_gradient=True)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    """reference nn.py data_norm → data_norm_op.cc: per-feature shift/scale
    from persistable batch statistics (CTR path; stats start at
    size=1e4, sum=0, square_sum=1e4 like the reference defaults)."""
    helper = LayerHelper("data_norm", **locals())
    dtype = helper.input_dtype()
    c = input.shape[-1]
    stat_attr = ParamAttr._to_attr(param_attr)

    def stat(name_suffix, value):
        attr = ParamAttr(
            name=(stat_attr.name + name_suffix) if stat_attr.name else None,
            initializer=ConstantInitializer(value), trainable=True)
        return helper.create_parameter(
            attr=attr, shape=[c], dtype=dtype, is_bias=False)

    batch_size = stat(".batch_size", 1e4)
    batch_sum = stat(".batch_sum", 0.0)
    batch_square_sum = stat(".batch_square_sum", 1e4)
    y = helper.create_variable_for_type_inference(dtype)
    means = helper.create_variable_for_type_inference(dtype, True)
    scales = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op(
        type="data_norm",
        inputs={"X": [input], "BatchSize": [batch_size],
                "BatchSum": [batch_sum],
                "BatchSquareSum": [batch_square_sum]},
        outputs={"Y": [y], "Means": [means], "Scales": [scales]},
        attrs={"epsilon": epsilon, "data_layout": data_layout},
    )
    return helper.append_activation(y)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference nn.py spectral_norm → spectral_norm_op.h"""
    helper = LayerHelper("spectral_norm", **locals())
    dtype = weight.dtype
    h = weight.shape[dim]
    w = 1
    for i, s in enumerate(weight.shape):
        if i != dim:
            w *= s
    u = helper.create_parameter(
        attr=ParamAttr(initializer=NormalInitializer(0.0, 1.0),
                       trainable=False),
        shape=[h], dtype=dtype, is_bias=False)
    v = helper.create_parameter(
        attr=ParamAttr(initializer=NormalInitializer(0.0, 1.0),
                       trainable=False),
        shape=[w], dtype=dtype, is_bias=False)
    u.stop_gradient = True
    v.stop_gradient = True
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="spectral_norm",
        inputs={"Weight": [weight], "U": [u], "V": [v]},
        outputs={"Out": [out]},
        attrs={"dim": dim, "power_iters": power_iters, "eps": eps},
    )
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference nn.py row_conv → row_conv_op.cc (padded [B,T,D] here)."""
    helper = LayerHelper("row_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="row_conv",
        inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [out]},
    )
    return helper.append_activation(out)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    """reference nn.py lrn → lrn_op.cc"""
    out, _ = _simple("lrn", {"X": input},
                     {"n": n, "k": k, "alpha": alpha, "beta": beta},
                     outs=("Out", "MidOut"))
    return out


# ---- vision rearrangements ---------------------------------------------

def pixel_shuffle(x, upscale_factor):
    """reference nn.py pixel_shuffle → pixel_shuffle_op.cc"""
    return _simple("pixel_shuffle", {"X": x},
                   {"upscale_factor": int(upscale_factor)})


def shuffle_channel(x, group, name=None):
    """reference nn.py shuffle_channel → shuffle_channel_op.cc"""
    return _simple("shuffle_channel", {"X": x}, {"group": int(group)})


def space_to_depth(x, blocksize, name=None):
    """reference nn.py space_to_depth → space_to_depth_op.cc"""
    return _simple("space_to_depth", {"X": x}, {"blocksize": int(blocksize)})


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    """reference nn.py temporal_shift → temporal_shift_op.cc"""
    return _simple("temporal_shift", {"X": x},
                   {"seg_num": int(seg_num),
                    "shift_ratio": float(shift_ratio)})


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    """reference nn.py affine_channel → affine_channel_op.cc"""
    return _simple("affine_channel",
                   {"X": x, "Scale": scale, "Bias": bias},
                   {"data_layout": data_layout})


def fsp_matrix(x, y):
    """reference nn.py fsp_matrix → fsp_op.cc"""
    return _simple("fsp", {"X": x, "Y": y})


def grid_sampler(x, grid, name=None):
    """reference nn.py grid_sampler → grid_sampler_op.cc"""
    helper = LayerHelper("grid_sampler", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="grid_sampler",
        inputs={"X": [x], "Grid": [grid]},
        outputs={"Output": [out]},
    )
    return out


def affine_grid(theta, out_shape, name=None):
    """reference nn.py affine_grid → affine_grid_op.cc"""
    if isinstance(out_shape, Variable):
        raise NotImplementedError(
            "affine_grid with a variable out_shape needs static shapes on "
            "TPU; pass a python list")
    helper = LayerHelper("affine_grid", **locals())
    out = helper.create_variable_for_type_inference(theta.dtype)
    helper.append_op(
        type="affine_grid", inputs={"Theta": [theta]},
        outputs={"Output": [out]},
        attrs={"output_shape": [int(s) for s in out_shape]})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_lod=None):
    """reference nn.py roi_pool → roi_pool_op.cc; rois [R,5] with a
    leading batch-index column (TPU-static replacement for ROI LoD)."""
    out, _ = _simple(
        "roi_pool", {"X": input, "ROIs": rois, "RoisLod": rois_lod},
        {"pooled_height": int(pooled_height),
         "pooled_width": int(pooled_width),
         "spatial_scale": float(spatial_scale)},
        outs=("Out", "Argmax"))
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    """reference nn.py psroi_pool → psroi_pool_op.cc"""
    return _simple(
        "psroi_pool", {"X": input, "ROIs": rois},
        {"output_channels": int(output_channels),
         "spatial_scale": float(spatial_scale),
         "pooled_height": int(pooled_height),
         "pooled_width": int(pooled_width)})


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """reference nn.py unfold → unfold_op.cc (im2col)."""
    def pair(v):
        return [int(v), int(v)] if isinstance(v, int) else [int(a) for a in v]

    pads = pair(paddings)
    if len(pads) == 2:
        pads = pads + pads
    return _simple(
        "unfold", {"X": x},
        {"kernel_sizes": pair(kernel_sizes), "strides": pair(strides),
         "paddings": pads, "dilations": pair(dilations)},
        outs=("Y",))


# ---- losses -------------------------------------------------------------

def log_loss(input, label, epsilon=1e-4, name=None):
    """reference nn.py log_loss → log_loss_op.h"""
    return _simple("log_loss", {"Predicted": input, "Labels": label},
                   {"epsilon": float(epsilon)}, outs=("Loss",))


def kldiv_loss(x, target, reduction="mean", name=None):
    """reference nn.py kldiv_loss → kldiv_loss_op.h"""
    return _simple("kldiv_loss", {"X": x, "Target": target},
                   {"reduction": reduction}, outs=("Loss",))


def rank_loss(label, left, right, name=None):
    """reference nn.py rank_loss → rank_loss_op.h (RankNet)"""
    return _simple("rank_loss",
                   {"Label": label, "Left": left, "Right": right})


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """reference nn.py margin_rank_loss → margin_rank_loss_op.h"""
    out, _ = _simple("margin_rank_loss",
                     {"Label": label, "X1": left, "X2": right},
                     {"margin": float(margin)},
                     outs=("Out", "Activated"))
    return out


def bpr_loss(input, label, name=None):
    """reference nn.py bpr_loss → bpr_loss_op.h"""
    return _simple("bpr_loss", {"X": input, "Label": label}, outs=("Y",))


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """reference nn.py → teacher_student_sigmoid_loss_op.h"""
    return _simple("teacher_student_sigmoid_loss",
                   {"X": input, "Label": label},
                   {"soft_max_up_bound": soft_max_up_bound,
                    "soft_max_lower_bound": soft_max_lower_bound},
                   outs=("Y",))


def mean_iou(input, label, num_classes):
    """reference nn.py mean_iou → mean_iou_op.h"""
    return _simple("mean_iou", {"Predictions": input, "Labels": label},
                   {"num_classes": int(num_classes)},
                   out_dtype="float32",
                   outs=("OutMeanIou", "OutWrong", "OutCorrect"),
                   stop_gradient=True)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference nn.py bilinear_tensor_product →
    bilinear_tensor_product_op.h"""
    helper = LayerHelper("bilinear_tensor_product", **locals())
    dtype = helper.input_dtype()
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[size, x.shape[1], y.shape[1]], dtype=dtype, is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        bias = helper.create_parameter(
            attr=helper.bias_attr, shape=[1, size], dtype=dtype,
            is_bias=True)
        inputs["Bias"] = [bias]
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


# ---- compositions (pure-python in the reference too) --------------------

def dice_loss(input, label, epsilon=1e-5):
    """reference nn.py dice_loss (composition)."""
    from . import nn as _nn
    from . import ops as _ops
    from . import tensor as _tensor

    label = _nn.one_hot(label, depth=input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = _nn.reduce_sum(_nn.elementwise_mul(input, label),
                          dim=reduce_dims)
    dice_denominator = _nn.elementwise_add(
        _nn.reduce_sum(input, dim=reduce_dims),
        _nn.reduce_sum(label, dim=reduce_dims))
    dice_score = _ops.scale(
        _nn.elementwise_div(
            _ops.scale(inse, scale=2.0),
            _ops.scale(dice_denominator, scale=1.0, bias=epsilon)),
        scale=-1.0, bias=1.0)
    return _nn.reduce_mean(dice_score)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference nn.py npair_loss (composition): cross-entropy over
    anchor@positive^T similarities + L2 on the embeddings."""
    from . import nn as _nn
    from . import ops as _ops

    batch = anchor.shape[0]
    sim = _nn.matmul(anchor, positive, transpose_y=True)
    lab = _nn.one_hot(labels, depth=batch)
    # row and column softmax cross entropies against the label matching
    ce_row = _nn.reduce_mean(_nn.softmax_with_cross_entropy(
        sim, lab, soft_label=True))
    ce_col = _nn.reduce_mean(_nn.softmax_with_cross_entropy(
        _nn.transpose(sim, perm=[1, 0]), lab, soft_label=True))
    l2 = _ops.scale(
        _nn.reduce_mean(
            _nn.elementwise_add(
                _nn.reduce_sum(_ops.square(anchor), dim=[1]),
                _nn.reduce_sum(_ops.square(positive), dim=[1]))),
        scale=float(l2_reg))
    return _nn.elementwise_add(
        _ops.scale(_nn.elementwise_add(ce_row, ce_col), 0.5), l2)


def rank(input):
    """reference nn.py rank: static ndim as a constant tensor."""
    from . import tensor as _tensor

    return _tensor.fill_constant([1], "int32", len(input.shape))


def sum(x):
    """reference nn.py sum → sum op (list fan-in add)."""
    return _simple("sum", {"X": list(x) if isinstance(x, (list, tuple))
                           else [x]})


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """reference nn.py image_resize_short (composition over image_resize).
    Static shapes on TPU: input H,W must be known."""
    from . import nn as _nn

    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    scale = float(out_short_len) / float(short)
    out_shape = [int(round(h * scale)), int(round(w * scale))]
    return _nn.image_resize(input, out_shape=out_shape, resample=resample)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference nn.py autoincreased_step_counter (persistable int counter
    incremented in-graph each step)."""
    from .. import unique_name
    from ..initializer import ConstantInitializer

    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    block = helper.main_program.global_block()
    if block.has_var(name):
        counter = block.var(name)
    else:
        counter = block.create_var(
            name=name, dtype="int64", shape=[1], persistable=True)
        counter.stop_gradient = True
        helper.set_variable_initializer(
            counter, ConstantInitializer(float(begin - step)))
    block.append_op(
        type="increment", inputs={"X": [counter]},
        outputs={"Out": [counter]}, attrs={"step": float(step)})
    return counter


# ---- wrappers over already-registered ops ------------------------------

def reduce_all(input, dim=None, keep_dim=False, name=None):
    """reference nn.py reduce_all → reduce_all op"""
    return _simple("reduce_all", {"X": input},
                   {"dim": dim if dim is not None else [],
                    "keep_dim": keep_dim, "reduce_all": dim is None},
                   out_dtype="bool", stop_gradient=True)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    """reference nn.py reduce_any → reduce_any op"""
    return _simple("reduce_any", {"X": input},
                   {"dim": dim if dim is not None else [],
                    "keep_dim": keep_dim, "reduce_all": dim is None},
                   out_dtype="bool", stop_gradient=True)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    """reference nn.py elementwise_mod op"""
    return _simple("elementwise_mod", {"X": x, "Y": y}, {"axis": axis})


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    """reference nn.py elementwise_floordiv op"""
    return _simple("elementwise_floordiv", {"X": x, "Y": y}, {"axis": axis})


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    """reference nn.py im2sequence → im2sequence_op.cc"""
    def pair(v):
        return [int(v), int(v)] if isinstance(v, int) else [int(a) for a in v]

    pads = pair(padding)
    if len(pads) == 2:
        pads = pads + pads
    return _simple(
        "im2sequence", {"X": input},
        {"kernels": pair(filter_size), "strides": pair(stride),
         "paddings": pads})
