"""Auto-generated thin layer wrappers over registered ops (reference:
``python/paddle/fluid/layers/ops.py``, generated from OpProtos by
``layer_function_generator.py``)."""

from ..layer_helper import LayerHelper

__all__ = [
    "exp", "tanh", "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin",
    "acos", "asin", "atan",
    "round", "reciprocal", "square", "softplus", "softsign", "logsigmoid",
    "sigmoid", "relu6", "pow", "stanh", "hard_sigmoid", "swish",
    "thresholded_relu", "hard_shrink", "softshrink", "elu", "gelu", "erf",
    "brelu", "soft_relu", "leaky_relu", "log", "scale", "hard_swish",
    "sign", "tanh_shrink", "cumsum", "uniform_random",
]


def _generate_unary(op_type):
    def func(x, name=None, **kwargs):
        helper = LayerHelper(op_type, **locals())
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(
            type=op_type, inputs={"X": [x]}, outputs={"Out": [out]},
            attrs={k: v for k, v in kwargs.items() if v is not None},
        )
        return out

    func.__name__ = op_type
    return func


exp = _generate_unary("exp")
tanh = _generate_unary("tanh")
sqrt = _generate_unary("sqrt")
rsqrt = _generate_unary("rsqrt")
abs = _generate_unary("abs")
ceil = _generate_unary("ceil")
floor = _generate_unary("floor")
cos = _generate_unary("cos")
sin = _generate_unary("sin")
round = _generate_unary("round")
reciprocal = _generate_unary("reciprocal")
square = _generate_unary("square")
softplus = _generate_unary("softplus")
softsign = _generate_unary("softsign")
logsigmoid = _generate_unary("logsigmoid")
sigmoid = _generate_unary("sigmoid")
relu6 = _generate_unary("relu6")
stanh = _generate_unary("stanh")
hard_sigmoid = _generate_unary("hard_sigmoid")
swish = _generate_unary("swish")
thresholded_relu = _generate_unary("thresholded_relu")
hard_shrink = _generate_unary("hard_shrink")
softshrink = _generate_unary("softshrink")
elu = _generate_unary("elu")
gelu = _generate_unary("gelu")
erf = _generate_unary("erf")
brelu = _generate_unary("brelu")
soft_relu = _generate_unary("soft_relu")
log = _generate_unary("log")
sign = _generate_unary("sign")
tanh_shrink = _generate_unary("tanh_shrink")
hard_swish = _generate_unary("hard_swish")


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="leaky_relu", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"alpha": alpha},
    )
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="pow", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"factor": float(factor)},
    )
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={
            "scale": float(scale),
            "bias": float(bias),
            "bias_after_scale": bias_after_scale,
        },
    )
    return helper.append_activation(out)


def _generate_binary_logical(op_type):
    def func(x, y, out=None, name=None):
        helper = LayerHelper(op_type, **locals())
        if out is None:
            out = helper.create_variable_for_type_inference("bool")
        helper.append_op(
            type=op_type, inputs={"X": [x], "Y": [y]},
            outputs={"Out": [out]},
        )
        return out

    func.__name__ = op_type
    return func


logical_and = _generate_binary_logical("logical_and")
logical_or = _generate_binary_logical("logical_or")
logical_xor = _generate_binary_logical("logical_xor")


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    helper.append_op(
        type="logical_not", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


__all__ += ["logical_and", "logical_or", "logical_xor", "logical_not"]


acos = _generate_unary("acos")
asin = _generate_unary("asin")
atan = _generate_unary("atan")


def cumsum(x, axis=None, exclusive=None, reverse=None):
    """reference layers/ops.py cumsum (cum_op.cc)."""
    helper = LayerHelper("cumsum", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {}
    if axis is not None:
        attrs["axis"] = int(axis)
    if exclusive is not None:
        attrs["exclusive"] = bool(exclusive)
    if reverse is not None:
        attrs["reverse"] = bool(reverse)
    helper.append_op(type="cumsum", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    """reference layers/ops.py uniform_random (uniform_random_op.cc)."""
    helper = LayerHelper("uniform_random", **locals())
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op(
        type="uniform_random", inputs={}, outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "min": float(min),
               "max": float(max), "seed": int(seed)},
    )
    return out
