"""LR schedules as graph ops (reference:
``python/paddle/fluid/layers/learning_rate_scheduler.py`` — each decay is a
small subgraph reading a global step counter).

TPU note: the schedule subgraph lowers into the same jitted step function as
the rest of the program, so there's no host round-trip per step; the global
step counter is a persistable scalar updated in-graph."""

import math

from ..framework import default_main_program
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer
from . import tensor
from . import ops

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "noam_decay",
    "cosine_decay",
    "linear_lr_warmup",
]


def _global_step(counter_name="@LR_DECAY_COUNTER@"):
    """Autoincrementing global step var (reference
    layers/tensor.py autoincreased_step_counter)."""
    helper = LayerHelper("global_step_counter")
    block = default_main_program().global_block()
    if block.has_var(counter_name):
        counter = block.var(counter_name)
    else:
        counter = block.create_var(
            name=counter_name, dtype="float32", shape=[1], persistable=True
        )
        helper.set_variable_initializer(counter, ConstantInitializer(0.0))
        block._prepend_op(
            type="increment",
            inputs={"X": [counter]},
            outputs={"Out": [counter]},
            # LRSched role (reference op_role enum): clone(for_test)
            # prunes it — an eval batch must not advance the decay
            # counter of the shared training scope
            attrs={"step": 1.0, "op_role": "lr_sched"},
        )
        counter.stop_gradient = True
    return counter


def noam_decay(d_model, warmup_steps):
    from .nn import elementwise_min

    step = _global_step()
    a = ops.pow(step, -0.5)
    b = step * (warmup_steps ** -1.5)
    return elementwise_min(a, b) * (d_model ** -0.5)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return _pow_scalar_base(decay_rate, div) * float(learning_rate)


def _pow_scalar_base(base, exponent_var):
    """base ** x as exp(x * ln(base)) using graph ops."""
    return ops.exp(exponent_var * math.log(base))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return learning_rate * ops.exp(div * (-decay_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    denom = div * decay_rate + 1.0
    return (tensor.fill_constant([1], "float32", learning_rate)) / denom


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    """reference learning_rate_scheduler.py:253 — with ``cycle`` the decay
    horizon stretches to decay_steps*ceil(step/decay_steps) (restarting
    each period); the reference's Switch on step==0 becomes a where."""
    from .nn import elementwise_min, where
    from .control_flow import equal

    step = _global_step()
    if cycle:
        div = ops.ceil(step / float(decay_steps))
        one = tensor.fill_constant([1], "float32", 1.0)
        zero = tensor.fill_constant([1], "float32", 0.0)
        div = where(equal(step, zero), one, div)
        frac = step / (div * float(decay_steps))
    else:
        capped = elementwise_min(
            step, tensor.fill_constant([1], "float32", float(decay_steps))
        )
        frac = capped / float(decay_steps)
    one_minus = frac * (-1.0) + 1.0
    return (learning_rate - end_learning_rate) * ops.pow(
        one_minus, power
    ) + end_learning_rate


def piecewise_decay(boundaries, values):
    import numpy as np

    from .nn import where

    step = _global_step()
    lr = tensor.fill_constant([1], "float32", values[-1])
    # chained where's, evaluated right-to-left
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        cond = step < float(b)
        lr = where(cond, tensor.fill_constant([1], "float32", v), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _global_step()
    epoch = ops.floor(step / float(step_each_epoch))
    inner = epoch * (math.pi / float(epochs))
    return 0.5 * learning_rate * (ops.cos(inner) + 1.0)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from .nn import where

    step = _global_step()
    warm = start_lr + (end_lr - start_lr) * (step / float(warmup_steps))
    if not hasattr(learning_rate, "name"):
        learning_rate = tensor.fill_constant([1], "float32",
                                             float(learning_rate))
    return where(step < float(warmup_steps), warm, learning_rate)
