"""Layer-function codegen helpers (reference:
``python/paddle/fluid/layers/layer_function_generator.py`` — the
machinery that stamps out one-op Python layers and their docstrings;
``layers/ops.py`` is generated with it).

TPU-native: :func:`generate_layer_fn` builds the wrapper from the op
REGISTRY's OpDef (slots come from the registered lowering, not C++
OpProto), so any op registered with ``register_op`` gets a layer for
free — the same one-liner contract the reference uses."""

import functools
import warnings

from .. import unique_name  # noqa: F401  (parity: referenced by users)
from ..layer_helper import LayerHelper

__all__ = ["deprecated", "generate_layer_fn", "generate_activation_fn",
           "autodoc", "templatedoc"]


def generate_layer_fn(op_type):
    """Return a Python layer function for a registered op: inputs become
    positional/keyword args by slot name, attrs pass via kwargs, and a
    fresh output var is created per output slot (first slot returned)."""
    from ..ops.registry import get_op_def

    opdef = get_op_def(op_type)
    in_slots = [s for s, _ in opdef.inputs]
    out_slots = [s for s, _ in opdef.outputs]

    def layer_fn(*args, **kwargs):
        helper = LayerHelper(op_type, **kwargs)
        if len(args) > len(in_slots):
            raise TypeError(
                "%s() takes at most %d positional inputs (%s), got %d"
                % (op_type, len(in_slots), in_slots, len(args)))
        inputs = {}
        for slot, val in zip(in_slots, args):
            if val is not None:
                inputs[slot] = val if isinstance(val, list) else [val]
        for slot in in_slots:
            if slot in kwargs:
                if slot in inputs:
                    raise TypeError(
                        "%s() got input slot %r both positionally and "
                        "as a keyword" % (op_type, slot))
                v = kwargs.pop(slot)
                if v is not None:
                    inputs[slot] = v if isinstance(v, list) else [v]
        kwargs.pop("name", None)
        dtype = None
        for vs in inputs.values():
            if vs and getattr(vs[0], "dtype", None) is not None:
                dtype = vs[0].dtype
                break
        outs = {}
        out_vars = []
        for slot in out_slots:
            v = helper.create_variable_for_type_inference(
                dtype or "float32")
            outs[slot] = [v]
            out_vars.append(v)
        helper.append_op(type=op_type, inputs=inputs, outputs=outs,
                         attrs=kwargs)
        return out_vars[0] if len(out_vars) == 1 else out_vars

    layer_fn.__name__ = op_type
    layer_fn.__doc__ = "Auto-generated layer for the %r op." % op_type
    return layer_fn


def generate_activation_fn(op_type):
    """Single-input single-output variant (reference's act-op stamp)."""
    fn = generate_layer_fn(op_type)

    def act_fn(x, name=None):
        return fn(X=x, name=name)

    act_fn.__name__ = op_type
    act_fn.__doc__ = "Auto-generated activation layer for %r." % op_type
    return act_fn


def deprecated(func_or_class):
    """Warn on use (reference :263)."""

    @functools.wraps(func_or_class)
    def wrapper(*args, **kwargs):
        warnings.warn(
            "API %r is deprecated" % func_or_class.__name__,
            DeprecationWarning)
        return func_or_class(*args, **kwargs)

    return wrapper


def autodoc(comment=""):
    """Prepend a comment to the wrapped function's docstring
    (reference :285)."""

    def impl(func):
        func.__doc__ = comment + (func.__doc__ or "")
        return func

    return impl


def templatedoc(op_type=None):
    """Reference fills ${...} docstring slots from the C++ OpProto; the
    registry has no prose metadata, so this resolves the slots to the
    op type name — keeping decorated code importable and the decorator
    API intact."""

    def impl(func):
        doc = func.__doc__ or ""
        t = op_type or func.__name__
        func.__doc__ = doc.replace("${comment}", "the %r op" % t)
        return func

    return impl
